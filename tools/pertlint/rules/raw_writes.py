"""PL013: raw checkpoint-like writes bypassing the atomic-commit
primitive.

Every durable artifact in the package — checkpoints, the run manifest,
the sharded-generation commit pointer, the Prometheus textfile — goes
through ``utils/fileio.py::atomic_write_bytes`` (mkstemp + write +
fsync + ``os.replace``), because the durability contract (OBSERVABILITY
"Durable runs") promises a preemption mid-write can never leave a torn
file visible to ``--resume auto``.  A direct ``np.savez(path, ...)`` or
``open(path, 'wb')`` re-introduces exactly the crash window the
two-phase commit exists to close: the file exists, half-written, with
no integrity footer committed — and the NEXT run trusts it.

Precision contract (what keeps this rule quiet on correct code):

* ``np.savez``/``np.savez_compressed``/``np.save`` fire only when the
  first argument is not an obvious in-memory buffer: a name containing
  ``buf``/``bio``/``stream``, or a direct ``io.BytesIO(...)`` call, is
  the sanctioned serialise-to-memory idiom (the caller then commits the
  bytes atomically, footer included);
* ``open(..., mode)`` fires only for BINARY WRITE modes (a ``b`` plus
  any of ``w``/``x``/``a`` in a literal mode string) — text-mode writes
  (reports, markdown) are not durability-bearing artifacts, and read
  modes never match; a non-literal mode cannot be judged and is exempt;
* only the builtin ``open`` NAME fires (``os.fdopen`` inside the
  primitive itself, ``gzip.open`` readers etc. are attribute calls or
  different names);
* ``utils/fileio.py`` is exempt by path — it IS the primitive;
* a deliberate raw write stays expressible with the inline suppression
  (``# pertlint: disable=PL013``) carrying its why, or a baseline
  entry with a rationale.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.pertlint.core import Finding, Rule, register

_NP_WRITERS = {"savez", "savez_compressed", "save"}
_BUFFERISH = ("buf", "bio", "stream")


def _is_buffer_arg(arg: ast.expr) -> bool:
    """Does the first np.savez argument look like an in-memory buffer?"""
    if isinstance(arg, ast.Name):
        return any(tok in arg.id.lower() for tok in _BUFFERISH)
    if isinstance(arg, ast.Call):
        func = arg.func
        if isinstance(func, ast.Name) and func.id == "BytesIO":
            return True
        if isinstance(func, ast.Attribute) and func.attr == "BytesIO":
            return True
    return False


def _binary_write_mode(call: ast.Call):
    """The literal mode string when this ``open`` call writes binary,
    else None (read modes, text modes, non-literal modes)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
    if not isinstance(mode, ast.Constant) or not isinstance(mode.value,
                                                            str):
        return None
    value = mode.value
    if "b" in value and any(m in value for m in ("w", "x", "a")):
        return value
    return None


@register
class RawDurableWrite(Rule):
    id = "PL013"
    name = "raw-checkpoint-write"
    severity = "error"
    description = ("direct np.savez/open(..., 'wb') write that bypasses "
                   "utils/fileio.atomic_write_bytes — a crash mid-write "
                   "leaves a torn artifact visible to --resume auto; "
                   "serialise to memory and commit atomically")

    def check(self, ctx) -> Iterable[Finding]:
        path = str(ctx.path).replace("\\", "/")
        if path.endswith("utils/fileio.py"):
            return   # the primitive's own fd plumbing lives here
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _NP_WRITERS \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in ("np", "numpy"):
                if node.args and _is_buffer_arg(node.args[0]):
                    continue
                yield self.finding(
                    ctx, node,
                    f"np.{func.attr}(...) writes a durable artifact "
                    f"directly to its path — a crash mid-write leaves a "
                    f"torn, footerless file the next --resume auto "
                    f"trusts; serialise to an in-memory buffer and "
                    f"commit through utils/fileio.atomic_write_bytes")
            elif isinstance(func, ast.Name) and func.id == "open":
                mode = _binary_write_mode(node)
                if mode is None:
                    continue
                yield self.finding(
                    ctx, node,
                    f"open(..., {mode!r}) writes binary bytes in place "
                    f"— a checkpoint-like artifact must go through "
                    f"utils/fileio.atomic_write_bytes (mkstemp + fsync "
                    f"+ os.replace) so a preemption mid-write can never "
                    f"leave a torn file visible to --resume auto")
