"""PL011: swallowed broad exception handlers in library modules.

The durability layer (``infer/runner.py``'s retry/degradation ladder,
``utils/faults.py``'s exception taxonomy) only works when failures are
VISIBLE: a ``except Exception:`` block that neither re-raises nor
reports turns a preemption, OOM or real bug into silent state
corruption — the run "succeeds" with whatever half-state the handler
left behind, and no RunLog event or log line ever says why the output
is wrong.  The observability contract (OBSERVABILITY.md) allows
deliberate best-effort swallows (telemetry must not take down a fit),
but they must be *auditable*: re-raise, emit a RunLog event, or log
through the package logger.

Precision contract (what keeps this rule quiet on correct code):

* only BROAD handlers fire: a bare ``except:``, ``except Exception:``,
  ``except BaseException:``, or a tuple containing either name.
  Narrow handlers (``except OSError:``) encode a considered decision
  about a specific failure mode and are exempt;
* a handler is NOT swallowed when its body (nested nodes included)
  contains any of: a ``raise`` statement; a RunLog ``.emit(...)`` call
  (same receiver heuristic as PL009 — names/attributes containing
  ``log``, ``current()``, ``self`` inside a ``*Log*`` class); a call
  through a logger (``logger.warning(...)``, ``logging.warning(...)``,
  any receiver whose name contains ``log``); or ``warnings.warn(...)``;
* deliberate silent swallows remain expressible with the standard
  inline suppression (``# pertlint: disable=PL011``) carrying its why —
  the point is that silence must be a visible, reviewed decision.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.pertlint.core import Finding, Rule, register
from tools.pertlint.rules.event_kinds import _is_runlog_receiver

_BROAD_NAMES = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True   # bare except:
    if isinstance(t, ast.Name):
        return t.id in _BROAD_NAMES
    if isinstance(t, ast.Attribute):
        return t.attr in _BROAD_NAMES   # builtins.Exception etc.
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD_NAMES
                   or isinstance(e, ast.Attribute)
                   and e.attr in _BROAD_NAMES
                   for e in t.elts)
    return False


def _receiver_mentions_log(func: ast.Attribute) -> bool:
    """Is this attribute call routed through something log-shaped?
    (``logger.warning``, ``logging.warning``, ``profiling.logger.x``)."""
    value = func.value
    if isinstance(value, ast.Name):
        return "log" in value.id.lower()
    if isinstance(value, ast.Attribute):
        return "log" in value.attr.lower()
    return False


def _handles(handler: ast.ExceptHandler, ctx) -> bool:
    """Does the handler body re-raise or report the exception?"""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr == "emit" \
                and _is_runlog_receiver(func.value, node, ctx):
            return True
        if func.attr == "warn" and isinstance(func.value, ast.Name) \
                and func.value.id == "warnings":
            return True
        if func.attr in ("debug", "info", "warning", "error",
                         "exception", "critical", "log") \
                and _receiver_mentions_log(func):
            return True
    return False


@register
class SwallowedException(Rule):
    id = "PL011"
    name = "swallowed-exception-in-library"
    severity = "error"
    description = ("bare except: / except Exception: block that neither "
                   "re-raises nor reports (RunLog event, package logger, "
                   "warnings.warn) — silent failure corrupts the "
                   "durability layer's audit trail; report or re-raise, "
                   "or suppress inline with the WHY")

    def check(self, ctx) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _handles(node, ctx):
                continue
            kind = ("bare except:" if node.type is None else
                    f"except {ast.unparse(node.type)}:")
            yield self.finding(
                ctx, node,
                f"{kind} swallows the exception without re-raising or "
                f"reporting it (no raise, no RunLog .emit, no logger "
                f"call, no warnings.warn) — a preemption/OOM/bug "
                f"disappears here with no audit trail; report it, "
                f"re-raise it, or suppress inline with the rationale")
