"""Rule modules — importing this package registers every rule."""

from tools.pertlint.rules import (  # noqa: F401
    control_actions,
    donate,
    dtype_drift,
    event_kinds,
    host_sync,
    jit_in_loop,
    metric_names,
    partition_spec,
    print_log,
    raw_writes,
    rng,
    span_names,
    swallowed,
    tracer_branch,
)
