"""PL009: RunLog event kinds must exist in the schema's event enum.

The telemetry contract is the checked-in JSON schema
(``scdna_replication_tools_tpu/obs/runlog_schema.json``): every event a
run emits is validated against it by tests and by downstream tooling
(``obs/schema.py``, ``tools/pert_report.py``).  An ``emit("...")`` call
site whose kind is missing from the schema enum produces events that
FAIL validation at runtime — but only when a test happens to exercise
that exact code path, and the RunLog's never-raise discipline means
production just writes an invalid artifact.  This rule closes the gap
statically: the AST scan cross-checks every literal event kind at a
RunLog emit call site against the enum, so adding an event without
registering it in the schema is a lint error at commit time, not a
schema violation discovered in an artifact three rounds later.

Precision contract (what keeps this rule quiet on correct code):

* only ``.emit("<literal>", ...)`` attribute calls fire, and only when
  the receiver is recognisably a RunLog: a name/attribute containing
  ``log`` (``run_log``, ``self.run_log``, a bare ``log``), the
  ``current()`` accessor (``_runlog.current().emit(...)`` — the seam
  ``infer/svi.py`` uses), or ``self`` inside a ``*Log*`` class
  (``obs/runlog.py``'s own lifecycle emits);
* non-literal kinds (``emit(kind)``) are skipped — they cannot be
  checked statically and the runtime validator still covers them;
* other ``.emit`` APIs (signal buses, Qt, etc.) never match the
  receiver heuristic.
"""

from __future__ import annotations

import ast
import functools
import json
import pathlib
from typing import FrozenSet, Iterable, Optional

from tools.pertlint.core import Finding, Rule, register

_SCHEMA_PATH = (pathlib.Path(__file__).resolve().parents[3]
                / "scdna_replication_tools_tpu" / "obs"
                / "runlog_schema.json")

_RECEIVER_HINT = "log"


@functools.lru_cache(maxsize=1)
def schema_event_kinds() -> FrozenSet[str]:
    """The event enum pinned by the checked-in run-log schema; empty when
    the schema is unreadable (the rule then stays silent — a missing
    schema is the schema tests' problem, not a lint crash)."""
    try:
        doc = json.loads(_SCHEMA_PATH.read_text())
        return frozenset(doc["properties"]["event"]["enum"])
    except (OSError, KeyError, TypeError, ValueError):
        return frozenset()


def _enclosing_log_class(node, ctx) -> bool:
    """Is ``node`` lexically inside a class whose name contains 'Log'?"""
    cursor = ctx.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, ast.ClassDef) and "Log" in cursor.name:
            return True
        cursor = ctx.parents.get(cursor)
    return False


def _is_runlog_receiver(value, node, ctx) -> bool:
    """Does the ``.emit`` receiver look like a RunLog instance?"""
    if isinstance(value, ast.Name):
        if value.id == "self":
            return _enclosing_log_class(node, ctx)
        return _RECEIVER_HINT in value.id.lower()
    if isinstance(value, ast.Attribute):
        return _RECEIVER_HINT in value.attr.lower()
    if isinstance(value, ast.Call):
        func = value.func
        name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else "")
        return name == "current"
    return False


@register
class UnknownRunLogEventKind(Rule):
    id = "PL009"
    name = "unknown-runlog-event-kind"
    severity = "error"
    description = ("RunLog .emit('<kind>') call site whose event kind is "
                   "not in the event enum of obs/runlog_schema.json — the "
                   "emitted events fail schema validation; register the "
                   "kind (with its payload contract) in the schema first")

    def __init__(self, kinds: Optional[Iterable[str]] = None):
        # injectable for tests; default = the checked-in schema enum
        self._kinds = (schema_event_kinds() if kinds is None
                       else frozenset(kinds))

    def check(self, ctx) -> Iterable[Finding]:
        if not self._kinds:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            if not _is_runlog_receiver(node.func.value, node, ctx):
                continue
            kind = node.args[0].value
            if kind not in self._kinds:
                yield self.finding(
                    ctx, node,
                    f"RunLog event kind {kind!r} is not in the event enum "
                    f"of obs/runlog_schema.json — emitted events will "
                    f"fail schema validation; add the kind and its "
                    f"payload contract to the schema (and bump "
                    f"SCHEMA_VERSION if the vocabulary changes meaning)")
