"""PL007: jit entry point takes initial-value pytrees without donation.

A jit-compiled optimisation entry that takes *initial-value* pytree
arguments — the ``params0`` / ``opt_state0`` / ``losses0`` / ``*_init``
naming convention marks buffers that are dead the moment the compiled
program consumes them — should donate those arguments
(``donate_argnums`` / ``donate_argnames``).  Without donation XLA copies
every such buffer on entry: at the package's 10k-cell scale the
``pi_logits`` plane alone is ~2.8 GB of pointless HBM churn per fit
(the lineage of this rule is ``infer/svi.py:_run_fit``, which ran
undonated through round 5).

Precision contract (what keeps this rule quiet on correct code):

* only parameter NAMES following the initial-value convention trigger —
  a stem in {params, opt_state, state, losses, carry, buffers} with a
  ``0`` / ``_0`` / ``_init`` suffix.  A plain ``params`` argument (e.g.
  a decode entry that must NOT donate, because the caller reuses the
  fitted params across slabs) never fires;
* any ``donate_argnums``/``donate_argnames`` on the jit wrapping —
  regardless of which arguments it names — silences the rule: the
  author has made a donation decision;
* only ``jit``/``pjit`` wrappings are inspected (donation is a jit
  contract; ``shard_map`` has no such kwarg).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from tools.pertlint.core import Finding, Rule, register

_STEMS = ("params", "opt_state", "state", "losses", "carry", "buffers")
_INIT_VALUE = re.compile(rf"^(?:{'|'.join(_STEMS)})(?:0|_0|_init)$")

_DONATE_KWARGS = {"donate_argnums", "donate_argnames"}
_JIT_NAMES = {"jit", "pjit"}


def _tail(expr: ast.AST) -> Optional[str]:
    """'jit' for ``jit`` / ``jax.jit`` / ``jax.experimental.pjit.pjit``."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _jit_call(call: ast.Call) -> bool:
    """Does ``call`` build a jit/pjit wrapper (directly or via partial)?"""
    if _tail(call.func) in _JIT_NAMES:
        return True
    return (_tail(call.func) == "partial" and call.args
            and _tail(call.args[0]) in _JIT_NAMES)


def _donates(call: Optional[ast.Call]) -> bool:
    if call is None:
        return False  # bare ``@jax.jit`` — no kwargs at all
    return any(kw.arg in _DONATE_KWARGS for kw in call.keywords)


def _init_value_args(func: ast.AST) -> List[str]:
    a = func.args
    names = [arg.arg for arg in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    return [n for n in names if _INIT_VALUE.match(n)]


@register
class UndonatedInitBuffers(Rule):
    id = "PL007"
    name = "undonated-init-buffers"
    severity = "error"
    description = ("jit entry point takes initial-value pytree arguments "
                   "(params0/opt_state0/.../*_init) without "
                   "donate_argnums/donate_argnames — every fit copies "
                   "those buffers on entry")

    def check(self, ctx) -> Iterable[Finding]:
        funcs = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, []).append(node)
                yield from self._check_decorated(ctx, node)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _jit_call(node):
                # jax.jit(f, ...) / partial(jax.jit, ...) applied directly
                yield from self._check_call_site(ctx, node, node, funcs)
            elif isinstance(node.func, ast.Call) and _jit_call(node.func):
                # partial(jax.jit, ...)(f): donation kwargs live on the
                # inner partial call, the wrapped fn on the outer one
                yield from self._check_call_site(ctx, node, node.func,
                                                 funcs)

    def _message(self, func_name: str, init_args: List[str]) -> str:
        return (f"jit wrapping of {func_name!r} takes initial-value "
                f"pytree argument(s) {', '.join(sorted(init_args))} "
                f"without donate_argnums/donate_argnames; donate them "
                f"(dead after entry by the 0/_init naming convention) "
                f"or rename if the caller really reuses the buffers")

    def _check_decorated(self, ctx, func) -> Iterable[Finding]:
        init_args = _init_value_args(func)
        if not init_args:
            return
        for dec in func.decorator_list:
            call = dec if isinstance(dec, ast.Call) else None
            is_jit = (_tail(dec) in _JIT_NAMES if call is None
                      else _jit_call(call))
            if is_jit and not _donates(call):
                yield self.finding(ctx, func,
                                   self._message(func.name, init_args))
                return  # one finding per function, not per decorator

    def _check_call_site(self, ctx, call: ast.Call, wrapper_call: ast.Call,
                         funcs) -> Iterable[Finding]:
        # resolve the wrapped same-module function by name from ``call``'s
        # args; donation kwargs are read from ``wrapper_call`` (the same
        # node for jax.jit(f, ...), the inner call for partial(...)(f))
        wrapped = None
        for arg in call.args:
            if isinstance(arg, ast.Name) and arg.id in funcs:
                wrapped = arg.id
                break
        if wrapped is None or _donates(wrapper_call) or _donates(call):
            return
        for func in funcs[wrapped]:
            if any(d for d in func.decorator_list):
                continue  # decorated defs are handled above
            init_args = _init_value_args(func)
            if init_args:
                yield self.finding(ctx, call,
                                   self._message(wrapped, init_args))
                return
