"""PL006: jax.jit constructed inside a loop — a recompilation hazard.

``jax.jit`` returns a *new* wrapped callable with its own compilation
cache; constructing one per loop iteration (or per call of a hot
function) recompiles the target every time, turning a microsecond
dispatch into a seconds-long XLA compile.  The fix is to hoist the
``jit`` (module level, or ``functools.partial`` applied once) — the
package's own drivers compile exactly once per fit (infer/svi.py) and
the benchmark deliberately scans all iterations inside one program
(bench.py) for the same reason.

Flagged: ``jit(...)`` / ``jax.jit(...)`` / ``partial(jax.jit, ...)``
call expressions lexically inside a ``for``/``while`` body (including
comprehensions).  Decorators are statements, not loop bodies, and never
match.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.pertlint import jitgraph
from tools.pertlint.core import Finding, Rule, register

_LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
          ast.DictComp, ast.GeneratorExp)


@register
class JitInLoop(Rule):
    id = "PL006"
    name = "jit-in-loop"
    severity = "error"
    description = ("jax.jit / partial(jax.jit, ...) constructed inside a "
                   "loop recompiles per iteration; hoist it")

    def check(self, ctx) -> Iterable[Finding]:
        parents = ctx.parents
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            is_jit = jitgraph.is_wrapper_expr(node.func) or (
                jitgraph._tail_name(node.func) == "partial"
                and node.args and jitgraph.is_wrapper_expr(node.args[0]))
            if not is_jit:
                continue
            # walk ancestors; a decorator position never sits under a loop
            cur = node
            while cur in parents:
                cur = parents[cur]
                if isinstance(cur, _LOOPS):
                    yield self.finding(
                        ctx, node,
                        "jax.jit constructed inside a loop builds a fresh "
                        "compilation cache every iteration (recompiles "
                        "each time); hoist the jit outside the loop")
                    break
