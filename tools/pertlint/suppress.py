"""Inline suppression comments.

``# pertlint: disable=PL001`` (or ``disable=PL001,PL004``) on a line
suppresses those rules for findings anchored to that line.  ``disable``
with no ``=RULE`` list suppresses every rule on the line.  A whole-file
opt-out is ``# pertlint: disable-file=PL003`` on any line (intended for
the top of the module, next to the reason).

Comments are found with :mod:`tokenize` rather than a substring scan so
a string literal containing the marker text can never suppress anything.

Malformed markers fail CLOSED: a typo'd keyword (``disable-files=``) or
a rule list with no valid rule id suppresses nothing — a silent
widen-to-everything here would turn a typo into a disabled CI gate.
Valid ids cover all three layers (``PLnnn`` ast, ``DPnnn`` deep,
``FLnnn`` flow) and are case-normalised (``pl005`` works).
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, Set, Tuple

# the kind must be followed by '=', whitespace or end-of-comment, so
# 'disable-files=' / 'disabled' don't half-match as a bare 'disable'
_MARKER = re.compile(
    r"#\s*pertlint:\s*(?P<kind>disable(?:-file)?)(?=[\s=]|$)"
    r"(?:\s*=\s*(?P<rules>[A-Za-z0-9_,\s]+))?")
_RULE_ID = re.compile(r"(?:PL|DP|FL)\d{3}$")

ALL = "*"


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """-> (line -> suppressed rule ids, file-wide suppressed rule ids).

    Rule-id sets may contain :data:`ALL`, meaning every rule.
    """
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(tok.start[0], tok.string) for tok in tokens
                    if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, file_wide

    for line, text in comments:
        m = _MARKER.search(text)
        if not m:
            continue
        if m.group("rules") is not None:
            rules = {r.strip().upper()
                     for r in m.group("rules").split(",") if r.strip()}
            rules = {r for r in rules if _RULE_ID.fullmatch(r)}
            if not rules:
                continue        # no valid rule id at all: fail closed
        else:
            rules = {ALL}       # bare 'disable' (no '='): everything
        if m.group("kind") == "disable-file":
            file_wide |= rules
        else:
            per_line.setdefault(line, set()).update(rules)
    return per_line, file_wide


def is_suppressed(rule_id: str, line: int,
                  per_line: Dict[int, Set[str]],
                  file_wide: Set[str]) -> bool:
    if ALL in file_wide or rule_id in file_wide:
        return True
    rules = per_line.get(line)
    return bool(rules) and (ALL in rules or rule_id in rules)
