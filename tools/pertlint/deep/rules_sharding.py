"""DP006/DP007 — the tensor-layout contract, machine-checked.

``layout.py`` calls itself the "single owner of the tensor-layout
contract"; these rules make that a checked invariant instead of a
docstring.  The engine resolves ``layout.contract_entries`` against the
canonical mesh (``entrypoints.MESH_EXTENTS``) and shapes
(``entrypoints.CANONICAL_DIMS``) into a :class:`trace.ContractContext`
of plain tuples; the core checker (:func:`check_spec_against_shape`) is
pure data-in/data-out so every failure mode has a direct unit test.

Findings anchor at the producing factory's def line in ``layout.py``.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Tuple

from tools.pertlint.core import Finding, register
from tools.pertlint.deep.rules_jaxpr import DeepRule

# problem codes the pure checker emits; DP006 and DP007 split them
RANK = "rank-overflow"
UNKNOWN = "unknown-axis"
REUSE = "axis-reuse"
INDIVISIBLE = "indivisible"


def check_spec_against_shape(spec: Tuple[Tuple[str, ...], ...],
                             spec_rank: int,
                             shape: Tuple[int, ...],
                             axis_extents: dict
                             ) -> List[Tuple[str, str]]:
    """Validate one normalised PartitionSpec against one array shape.

    ``spec`` is the per-dim tuple-of-axis-names form
    (``trace._normalise_spec``); ``spec_rank`` the raw PartitionSpec
    length (trailing ``None`` entries count — a rank-overflowing spec is
    a bug even when the overflow dims are unsharded, because it means
    the factory believes the tensor has a different rank than it does).
    Returns ``(code, message)`` problems; empty = the contract holds.
    """
    problems: List[Tuple[str, str]] = []
    if spec_rank > len(shape):
        problems.append((RANK,
                         f"spec rank {spec_rank} exceeds array rank "
                         f"{len(shape)} (shape {shape})"))
    used: dict = {}
    for d, axes in enumerate(spec[:len(shape)]):
        for ax in axes:
            if ax not in axis_extents:
                problems.append((UNKNOWN,
                                 f"dim {d} names mesh axis {ax!r} but the "
                                 f"mesh axes are "
                                 f"{sorted(axis_extents)}"))
            if ax in used:
                problems.append((REUSE,
                                 f"mesh axis {ax!r} appears on dim {d} and "
                                 f"dim {used[ax]} — an axis can shard at "
                                 f"most one dim"))
            used.setdefault(ax, d)
        extent = math.prod(axis_extents.get(ax, 1) for ax in axes)
        if extent > 1 and shape[d] % extent != 0:
            problems.append((INDIVISIBLE,
                             f"dim {d} (size {shape[d]}) is not divisible "
                             f"by its mesh extent {extent} "
                             f"({'*'.join(axes)}) — uneven shards mean "
                             f"per-device padding XLA hides until OOM/"
                             f"wrong-answer territory"))
    return problems


class ContractRule(DeepRule):
    """Base of the contract rules: ``check(ctx: ContractContext)``."""

    context = "contract"
    CODES: Tuple[str, ...] = ()

    def at_row(self, ctx, row, message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity, path=ctx.path,
                       line=row.line, col=0,
                       message=f"[{row.tensor}] {message}")

    def check(self, ctx) -> Iterable[Finding]:
        for row in ctx.rows:
            for code, msg in check_spec_against_shape(
                    row.spec, row.spec_rank, row.shape, ctx.axis_extents):
                if code in self.CODES:
                    yield self.at_row(ctx, row, msg)


@register
class ShardingContract(ContractRule):
    id = "DP006"
    name = "sharding-contract"
    severity = "error"
    description = ("a layout.py PartitionSpec factory violates the mesh "
                   "contract: spec rank exceeds the declared tensor rank, "
                   "names an unknown mesh axis, or reuses a mesh axis "
                   "across dims")
    CODES = (RANK, UNKNOWN, REUSE)


@register
class ShardingDivisibility(ContractRule):
    id = "DP007"
    name = "sharding-divisibility"
    severity = "error"
    description = ("a declared tensor dim is not divisible by the mesh "
                   "extent its PartitionSpec shards it over (canonical "
                   "shapes vs the 4x2 parity mesh)")
    CODES = (INDIVISIBLE,)
