"""DP001..DP005 — semantic rules over one traced program.

Each rule reads a :class:`tools.pertlint.deep.trace.ProgramContext` —
plain shapes/dtypes/strings, no jax objects — so this module imports
nothing outside the stdlib and every rule is unit-testable with a
hand-built context.  Findings anchor at the entry point's jit
decoration line, where the contract being violated is declared.
"""

from __future__ import annotations

from typing import Iterable, List

from tools.pertlint.core import Finding, Rule, register
from tools.pertlint.rules.donate import _INIT_VALUE

#: dtypes that must never appear in a traced PERT program: the pipeline
#: is f32-tuned end to end, and a single f64 intermediate doubles the
#: HBM stream of everything it touches (or crashes outright on TPU).
_WIDE_DTYPES = ("float64", "complex128")

#: host-transfer primitives: each is a device->host round trip baked
#: into a compiled program that the source-level PL001 can only guess at
_CALLBACK_PRIMS = ("callback", "debug_callback", "io_callback",
                   "pure_callback", "infeed", "outfeed")


class DeepRule(Rule):
    """Base of the jaxpr-level rules: ``check(ctx: ProgramContext)``."""

    kind = "deep"
    context = "program"

    def at(self, ctx, message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity, path=ctx.path,
                       line=ctx.line, col=0,
                       message=f"[{ctx.name}] {message}")


@register
class DtypePromotionAudit(DeepRule):
    id = "DP001"
    name = "dtype-promotion-audit"
    severity = "error"
    description = ("a traced program carries float64/complex128 values or "
                   "silently narrows f32 work to bf16 — the semantic "
                   "upgrade of the AST-level PL004 dtype guess")

    def check(self, ctx) -> Iterable[Finding]:
        wide = [a for a in ctx.var_avals if a.dtype in _WIDE_DTYPES]
        wide += [a for a in ctx.out_avals if a.dtype in _WIDE_DTYPES]
        if wide:
            kinds = sorted({a.dtype for a in wide})
            yield self.at(ctx, f"{len(wide)} {'/'.join(kinds)} value(s) in "
                               f"the traced program — the pipeline is "
                               f"f32-tuned; an x64 leak here doubles HBM "
                               f"traffic (check jax_enable_x64 and literal "
                               f"dtypes)")
        narrowed = [(src, dst) for src, dst in ctx.converts
                    if src.dtype == "float32" and dst == "bfloat16"]
        if narrowed:
            yield self.at(ctx, f"{len(narrowed)} convert_element_type "
                               f"f32->bf16 — silent precision drop in a "
                               f"program tuned for f32 accumulation; make "
                               f"the cast explicit policy or remove it")


@register
class HostCallbackInProgram(DeepRule):
    id = "DP002"
    name = "host-callback-in-program"
    severity = "error"
    description = ("a host callback / debug print / infeed primitive is "
                   "actually present in a traced program — each is a "
                   "device->host sync per call (the semantic upgrade of "
                   "PL001's source-level guess)")

    def check(self, ctx) -> Iterable[Finding]:
        for use in ctx.primitives:
            if use.name in _CALLBACK_PRIMS:
                yield self.at(ctx, f"primitive '{use.name}' x{use.count} in "
                                   f"the traced program — a host round-trip "
                                   f"inside compiled code (left-over "
                                   f"jax.debug.print / pure_callback?)")


@register
class DonationAudit(DeepRule):
    id = "DP003"
    name = "donation-audit"
    severity = "error"
    description = ("declared donate_argnames that produce no "
                   "input_output_alias in the lowered module (the PR-4 "
                   "mirror-rescue aliasing bug class), undonated "
                   "initial-value buffers, and donation typos")

    def check(self, ctx) -> Iterable[Finding]:
        # 1) donation typos: declared names that are not dynamic args
        for name in ctx.declared_donate:
            if name not in ctx.dynamic_arg_names:
                yield self.at(ctx, f"donate_argnames names {name!r} but the "
                                   f"program has no such dynamic argument — "
                                   f"the donation silently does nothing")

        # 2) donated-but-unaliased: XLA dropped the alias, so the caller
        # believes the buffer is recycled while the program copies it
        # (or worse, aliases live state — the PR-4 bug)
        unaliased: dict = {}
        for leaf in ctx.leaves:
            if leaf.donated and leaf.aliased is False:
                unaliased.setdefault(leaf.arg, []).append(leaf)
        for arg, leaves in sorted(unaliased.items()):
            total = sum(1 for l in ctx.leaves if l.arg == arg and l.donated)
            yield self.at(ctx, f"argument {arg!r}: {len(leaves)} of {total} "
                               f"donated leaves have NO input_output_alias "
                               f"in the lowered module — the donation is "
                               f"not happening (shape/dtype mismatch with "
                               f"every output, or the buffer is still "
                               f"live); first leaf: "
                               f"{arg}{leaves[0].keypath} "
                               f"{leaves[0].aval.shape}")
        if not unaliased and ctx.donated_leaf_count \
                and ctx.alias_count < ctx.donated_leaf_count:
            # attribution failed (MLIR arg count mismatch): fall back to
            # comparing totals so the audit cannot silently pass
            yield self.at(ctx, f"{ctx.donated_leaf_count} leaves are "
                               f"declared donated but only "
                               f"{ctx.alias_count} input_output_aliases "
                               f"exist in the lowered module")

        # 3) undonated initial-value buffers: the jaxpr-level twin of
        # PL007 — argument names following the *0/_init convention that
        # the jit wrapping does not donate
        for name in ctx.dynamic_arg_names:
            if _INIT_VALUE.match(name) and name not in ctx.declared_donate:
                nbytes = sum(l.aval.nbytes for l in ctx.leaves
                             if l.arg == name)
                yield self.at(ctx, f"initial-value argument {name!r} "
                                   f"(~{nbytes} bytes at the canonical "
                                   f"trace shape) is not donated — every "
                                   f"call copies it on entry")


@register
class ConstantBloat(DeepRule):
    id = "DP004"
    name = "constant-bloat"
    severity = "error"
    description = ("a large literal is baked into the traced program as a "
                   "closed-over constant — it is re-uploaded per program, "
                   "bloats the executable, and defeats the program cache "
                   "(equal fits stop being equal programs)")

    THRESHOLD_BYTES = 1 << 20  # 1 MiB: far above any legit scalar table

    def check(self, ctx) -> Iterable[Finding]:
        for const in ctx.consts:
            if const.nbytes > self.THRESHOLD_BYTES:
                yield self.at(ctx, f"closed-over constant {const.shape} "
                                   f"{const.dtype} ({const.nbytes} bytes) "
                                   f"baked into the jaxpr — pass it as an "
                                   f"argument so it lives once in HBM and "
                                   f"the program stays cacheable")


@register
class WhileCarryConsistency(DeepRule):
    id = "DP005"
    name = "while-carry-consistency"
    severity = "error"
    description = ("a lax.while_loop carry slot whose init and body-output "
                   "avals disagree (dtype/shape/weak-type) or that carries "
                   "a weak type — the _fit_loop carry must be bit-stable "
                   "across iterations or XLA inserts per-iteration casts")

    def check(self, ctx) -> Iterable[Finding]:
        for entry in ctx.while_carries:
            init, out = entry.init, entry.body_out
            if (init.shape, init.dtype) != (out.shape, out.dtype):
                yield self.at(ctx, f"while carry slot {entry.position}: "
                                   f"init {init.shape} {init.dtype} vs "
                                   f"body output {out.shape} {out.dtype} — "
                                   f"the loop re-lays-out its carry every "
                                   f"iteration")
            elif init.weak_type != out.weak_type:
                yield self.at(ctx, f"while carry slot {entry.position}: "
                                   f"weak-type flip between init "
                                   f"({init.weak_type}) and body output "
                                   f"({out.weak_type})")
            elif init.weak_type:
                yield self.at(ctx, f"while carry slot {entry.position} is "
                                   f"weakly typed ({init.dtype}) — a "
                                   f"Python scalar leaked into the carry; "
                                   f"pin it with jnp.asarray(..., dtype)")
