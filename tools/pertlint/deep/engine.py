"""Drive the deep pass and feed it through pertlint's shared machinery.

``deep_lint`` builds every registered entry point on abstract inputs,
traces/lowers each (CPU, nothing executes), resolves the layout
contract, runs the DP rules, then applies the SAME inline-suppression
and content-addressed-baseline filtering as the AST layer — so
``python -m tools.pertlint --deep`` is one gate with one workflow.

Deep findings anchor at real source lines (the jit decoration, the
layout factory def), which is what makes the shared machinery work:
an inline ``# pertlint: disable=DP003`` on that line suppresses, and the
baseline fingerprint is content-addressed to that line's text.  Deep
baseline entries are expected to carry a one-line ``rationale`` —
grandfathered *semantic* debt with no recorded WHY rots instantly — and
the run reports entries that lack one.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.pertlint import baseline as baseline_mod
from tools.pertlint import suppress
from tools.pertlint.core import Finding, Rule, all_rules
from tools.pertlint.engine import LintResult


@dataclasses.dataclass
class DeepStats:
    """Run facts the CLI reports next to the LintResult."""
    entrypoints: List[str]            # successfully traced entries
    skipped: List[str]                # builder skip reasons (devices)
    contract_rows: int = 0
    unrationalized: List[str] = dataclasses.field(default_factory=list)
    # fingerprints of matched DP baseline entries missing a rationale


def _ensure_cpu_devices(min_devices: int) -> None:
    """Force the multi-device CPU backend the placement entries need.

    Effective only when the jax backend is not yet initialised (the
    normal case for a fresh ``python -m tools.pertlint --deep``
    process); an already-initialised single-device backend just means
    the placement entries skip.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{min_devices}").strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — config key availability varies
        pass


def _deep_rules(select: Optional[Set[str]] = None) -> List[Rule]:
    rules = all_rules(kind="deep")
    if select is not None:
        rules = [r for r in rules if r.id in select]
    return rules


def run_deep_rules(select: Optional[Set[str]] = None,
                   entry_names: Optional[Sequence[str]] = None
                   ) -> Tuple[List[Finding], DeepStats]:
    """Trace the registry and run the DP rules -> raw (unfiltered)
    findings + stats.  Build/trace failures propagate: a gate that
    cannot see its programs must fail loudly, not shrink."""
    from tools.pertlint.deep import entrypoints, trace

    rules = _deep_rules(select)
    program_rules = [r for r in rules if r.context == "program"]
    contract_rules = [r for r in rules if r.context == "contract"]
    if not rules:
        # --deep --select <only-PL-ids>: nothing to run — do not pay
        # the tracing cost for zero rules
        return [], DeepStats(entrypoints=[], skipped=[])

    _ensure_cpu_devices(entrypoints.MESH_EXTENTS["cells"]
                        * entrypoints.MESH_EXTENTS["loci"])

    findings: List[Finding] = []
    traced: List[str] = []
    skipped: List[str] = []
    if program_rules:
        progs, skipped = entrypoints.build_all(
            list(entry_names) if entry_names is not None else None)
        for prog in progs:
            ctx = trace.build_program_context(prog)
            traced.append(prog.name)
            for rule in program_rules:
                findings.extend(rule.check(ctx))

    contract_rows = 0
    if contract_rules:
        ctx = trace.build_contract_context(entrypoints.CANONICAL_DIMS,
                                           entrypoints.MESH_EXTENTS)
        contract_rows = len(ctx.rows)
        for rule in contract_rules:
            findings.extend(rule.check(ctx))

    return findings, DeepStats(entrypoints=traced, skipped=skipped,
                               contract_rows=contract_rows)


def _filter_suppressed(findings: List[Finding],
                       sources: Dict[str, List[str]]
                       ) -> Tuple[List[Finding], List[Finding]]:
    kept: List[Finding] = []
    dropped: List[Finding] = []
    parsed: Dict[str, tuple] = {}
    for f in findings:
        if f.path not in parsed:
            text = "\n".join(sources.get(f.path, []))
            parsed[f.path] = suppress.parse_suppressions(text)
        per_line, file_wide = parsed[f.path]
        if suppress.is_suppressed(f.rule, f.line, per_line, file_wide):
            dropped.append(f)
        else:
            kept.append(f)
    return kept, dropped


def _load_sources(findings: List[Finding]) -> Dict[str, List[str]]:
    sources: Dict[str, List[str]] = {}
    for f in findings:
        if f.path in sources:
            continue
        p = pathlib.Path(f.path)
        sources[f.path] = p.read_text().splitlines() if p.is_file() else []
    return sources


def deep_lint(select: Optional[Set[str]] = None,
              baseline_path: Optional[pathlib.Path] = None
              ) -> Tuple[LintResult, DeepStats,
                         List[Tuple[Finding, str]]]:
    """The deep gate -> (result, stats, fingerprinted findings).

    The fingerprinted list (finding, fingerprint) covers ALL deep
    findings — the CLI folds it into ``--write-baseline`` /
    ``--update-baseline`` so the deep layer shares the one baseline
    file.
    """
    raw, stats = run_deep_rules(select)
    sources = _load_sources(raw)
    kept, suppressed = _filter_suppressed(raw, sources)
    fingerprinted = baseline_mod.fingerprint_findings(kept, sources)

    entries = baseline_mod.load_entries(baseline_path) if baseline_path \
        else []
    known = {e["fingerprint"] for e in entries}
    new = [f for f, fp in fingerprinted if fp not in known]
    baselined = [f for f, fp in fingerprinted if fp in known]

    produced = {fp for _, fp in fingerprinted}
    rule_ids = {r.id for r in _deep_rules(select)}
    stale = {e["fingerprint"] for e in entries
             if e["rule"] in rule_ids and e["fingerprint"] not in produced}
    # semantic debt needs a recorded WHY: matched DP entries lacking one
    rationale = baseline_mod.rationales(entries)
    matched = {fp for _, fp in fingerprinted if fp in known}
    stats.unrationalized = sorted(
        e["fingerprint"] for e in entries
        if e["fingerprint"] in matched and e["fingerprint"] not in rationale)

    result = LintResult(new=new, baselined=baselined,
                        suppressed=suppressed, stale_baseline=stale,
                        parse_errors=[], files_checked=len(sources))
    return result, stats, fingerprinted
