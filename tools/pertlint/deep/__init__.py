"""pertlint-deep: jaxpr- and sharding-level analysis of the traced pipeline.

The AST layer (``tools/pertlint/rules``) lints *source text*; this
package lints the *programs* XLA actually sees.  Because the package's
inference is trace-once/compile-once (one ``lax.while_loop`` per fit,
one compiled slab per decode), every dtype promotion, lost donation,
baked-in constant and sharding decision is statically visible in the
jaxpr and the lowered StableHLO **before anything runs** — so we check
them there, on abstract inputs (``jax.eval_shape`` / ``.trace()`` /
``.lower()`` on CPU; nothing is executed, no devices are required
beyond the forced-host CPU backend).

Layout:

* ``entrypoints.py`` — the registry of real jit entry points with
  canonical abstract shapes (fit, fit chunk, loss, decode slab, PPC,
  sharded batch/param placement);
* ``trace.py`` — turns one entry point into a ``ProgramContext``:
  closed jaxpr, flattened argument leaves with declared-donation and
  lowered input/output-alias facts, while-carry descriptors, constants;
* ``rules_jaxpr.py`` — DP001..DP005 over ``ProgramContext``;
* ``rules_sharding.py`` — DP006/DP007 over the machine-readable layout
  contract (``scdna_replication_tools_tpu.layout.contract_entries``);
* ``engine.py`` — drives it all and feeds findings through the SAME
  suppression + content-addressed-baseline machinery as the AST layer,
  so ``python -m tools.pertlint --deep`` is one gate.

Rule classes are stdlib-importable (``--list-rules`` works without
jax); jax is imported only when a deep run actually traces.
"""
