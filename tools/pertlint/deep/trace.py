"""Turn a registered entry point into the facts the deep rules consume.

Everything jax-flavoured happens HERE (and in ``entrypoints``): the rule
modules receive plain dataclasses of shapes/dtypes/strings, so they stay
stdlib-importable and their verdicts are trivially unit-testable with
hand-built contexts.

Nothing is ever executed: programs are traced (``.trace()``) and lowered
(``.lower()``) on abstract ``ShapeDtypeStruct`` arguments.  Donation
facts come from two independent sources that the DP003 audit compares —
the *declared* ``donate_argnames`` (via ``Lowered.args_info``) and the
*realised* ``tf.aliasing_output`` argument attributes of the lowered
StableHLO module.  A donated argument the lowering could not alias is
exactly the PR-4 mirror-rescue bug class (the donated buffer was silently
copied; worse, the caller believed it was dead while it aliased live
state).
"""

from __future__ import annotations

import dataclasses
import inspect
import math
import pathlib
import re
from typing import List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class AvalInfo:
    """Shape/dtype/weak-type of one abstract value, jax-free."""
    shape: Tuple[int, ...]
    dtype: str
    weak_type: bool = False

    @property
    def nbytes(self) -> int:
        import numpy as np

        return math.prod(self.shape) * np.dtype(self.dtype).itemsize


@dataclasses.dataclass(frozen=True)
class LeafInfo:
    """One flattened dynamic-argument leaf of a lowered program."""
    arg: str              # top-level dynamic argument name ("opt_state0")
    keypath: str          # pytree path inside that argument
    aval: AvalInfo
    donated: bool
    aliased: Optional[bool]   # None: MLIR positions could not be mapped


@dataclasses.dataclass(frozen=True)
class WhileCarryEntry:
    """One carry slot of a ``while`` eqn: init aval vs body-output aval."""
    position: int
    init: AvalInfo
    body_out: AvalInfo


@dataclasses.dataclass(frozen=True)
class PrimitiveUse:
    name: str
    count: int


@dataclasses.dataclass
class ProgramContext:
    """Everything DP001..DP005 need about one traced entry point."""
    name: str                 # registry name ("fit_chunk")
    path: str                 # repo-relative posix path of the anchor
    line: int                 # anchor line (the jit decoration/def)
    primitives: List[PrimitiveUse]
    out_avals: List[AvalInfo]
    var_avals: List[AvalInfo]         # every eqn output var, all sub-jaxprs
    converts: List[Tuple[AvalInfo, str]]   # convert_element_type: (in, out dtype)
    consts: List[AvalInfo]            # closed-over constants
    leaves: List[LeafInfo]
    declared_donate: Tuple[str, ...]
    dynamic_arg_names: Tuple[str, ...]
    while_carries: List[WhileCarryEntry]
    alias_count: int                  # tf.aliasing_output attrs in the MLIR
    donated_leaf_count: int


@dataclasses.dataclass
class ContractRow:
    """One layout-contract row, normalised to plain data."""
    tensor: str
    factory: str
    spec: Tuple[Tuple[str, ...], ...]   # per-dim tuple of mesh axis names
    spec_rank: int
    shape: Tuple[int, ...]
    line: int                           # factory's def line in layout.py


@dataclasses.dataclass
class ContractContext:
    """The whole layout contract against one canonical mesh."""
    path: str                 # repo-relative path of layout.py
    axis_extents: dict        # mesh axis name -> extent
    rows: List[ContractRow]


def repo_relpath(p: str) -> str:
    """Path relative to the CWD (the repo root in CI) when possible —
    findings and baseline fingerprints must match how the AST layer
    reports paths."""
    path = pathlib.Path(p).resolve()
    try:
        return path.relative_to(pathlib.Path.cwd().resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def anchor_of(fn) -> Tuple[str, int]:
    """(path, line) of a callable's definition, unwrapping jit wrappers.

    ``co_firstlineno`` of a decorated function is its first decorator
    line — exactly where a donation/static declaration lives.
    """
    while hasattr(fn, "__wrapped__"):
        fn = fn.__wrapped__
    code = getattr(fn, "__code__", None)
    if code is not None:
        return repo_relpath(code.co_filename), code.co_firstlineno
    # class instances (the value-hashable loss callables): anchor at the
    # class definition
    cls = type(fn)
    path = inspect.getsourcefile(cls)
    _, line = inspect.getsourcelines(cls)
    return repo_relpath(path), line


def _aval_info(aval) -> AvalInfo:
    return AvalInfo(shape=tuple(int(d) for d in getattr(aval, "shape", ())),
                    dtype=str(getattr(aval, "dtype", "")),
                    weak_type=bool(getattr(aval, "weak_type", False)))


def _sub_jaxprs(params: dict):
    for v in params.values():
        for cand in (v if isinstance(v, (list, tuple)) else (v,)):
            inner = getattr(cand, "jaxpr", None)
            if inner is None:
                continue
            # ClosedJaxpr.jaxpr -> Jaxpr (has .outvars); unwrap once more
            # if a doubly-closed jaxpr ever shows up
            yield inner if hasattr(inner, "outvars") else inner.jaxpr


def iter_eqns(jaxpr):
    """Depth-first over every eqn of ``jaxpr`` and all sub-jaxprs
    (while/cond/scan bodies, custom-derivative closures, ...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _while_carry_entries(eqn) -> List[WhileCarryEntry]:
    """Init-vs-body avals of one ``while`` eqn's carry slots."""
    cond_n = int(eqn.params.get("cond_nconsts", 0))
    body_n = int(eqn.params.get("body_nconsts", 0))
    carry_in = eqn.invars[cond_n + body_n:]
    body = eqn.params["body_jaxpr"]
    # ClosedJaxpr proxies .eqns but not .outvars — unwrap on that
    body_jaxpr = body if hasattr(body, "outvars") else body.jaxpr
    out = list(body_jaxpr.outvars)
    entries = []
    for i, (iv, ov) in enumerate(zip(carry_in, out)):
        entries.append(WhileCarryEntry(position=i, init=_aval_info(iv.aval),
                                       body_out=_aval_info(ov.aval)))
    return entries


_MAIN_SIG = re.compile(r"func\.func public @main\((.*?)\)\s*->", re.S)
_MAIN_ARG = re.compile(r"%arg(\d+): [^,)]+?(\{[^{}]*\})?(?=,|$|\))")


def parse_alias_positions(mlir_text: str
                          ) -> Tuple[Optional[int], frozenset]:
    """(argument count, positions carrying ``tf.aliasing_output``) of the
    lowered module's public main — None count when the signature could
    not be located (alias attribution then degrades to counting)."""
    m = _MAIN_SIG.search(mlir_text)
    if not m:
        return None, frozenset()
    sig = m.group(1)
    positions = set()
    count = 0
    for am in _MAIN_ARG.finditer(sig):
        count += 1
        if am.group(2) and "tf.aliasing_output" in am.group(2):
            positions.add(int(am.group(1)))
    return count, frozenset(positions)


def build_program_context(prog) -> ProgramContext:
    """Trace + lower one ``entrypoints.EntryProgram`` into plain facts."""
    import collections

    import jax

    traced = prog.jit_fn.trace(*prog.args, **prog.kwargs)
    closed = traced.jaxpr
    # Traced.lower() reuses the trace above; fn.lower() would re-trace
    # the whole program (the fit while_loop twice per gate run)
    lowered = traced.lower() if hasattr(traced, "lower") \
        else prog.jit_fn.lower(*prog.args, **prog.kwargs)
    text = lowered.as_text()

    # --- jaxpr walk -------------------------------------------------------
    prim_counts = collections.Counter()
    var_avals: List[AvalInfo] = []
    converts: List[Tuple[AvalInfo, str]] = []
    while_carries: List[WhileCarryEntry] = []
    for eqn in iter_eqns(closed.jaxpr):
        prim_counts[eqn.primitive.name] += 1
        for ov in eqn.outvars:
            var_avals.append(_aval_info(ov.aval))
        if eqn.primitive.name == "convert_element_type":
            converts.append((_aval_info(eqn.invars[0].aval),
                             str(eqn.params.get("new_dtype", ""))))
    # carry consistency is checked for TOP-LEVEL while loops only: those
    # are the package's own fit loops (the _fit_loop lineage).  Nested
    # whiles belong to jax library internals (e.g. jax.random.gamma's
    # rejection sampler carries a weak int on purpose) — flagging them
    # would make the gate track upstream implementation details.
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "while":
            while_carries.extend(_while_carry_entries(eqn))

    # closed-over constants are concrete arrays: read shape/dtype directly
    consts = [AvalInfo(shape=tuple(int(d)
                                   for d in getattr(c, "shape", ())),
                       dtype=str(getattr(c, "dtype", "")))
              for c in closed.consts]

    # --- argument leaves: declared donation vs realised aliasing ----------
    is_leaf = lambda x: hasattr(x, "donated")  # noqa: E731
    flat, _ = jax.tree_util.tree_flatten_with_path(lowered.args_info,
                                                   is_leaf=is_leaf)
    arg_count, aliased_pos = parse_alias_positions(text)
    attribute = arg_count is not None and arg_count == len(flat)

    # map each flat leaf to its top-level dynamic argument by leaf count
    names_by_leaf: List[Tuple[str, str]] = []
    for name, value in prog.dynamic_args:
        leaves = jax.tree_util.tree_flatten_with_path(
            value, is_leaf=lambda x: hasattr(x, "shape"))[0]
        for kp, _ in leaves:
            names_by_leaf.append((name, jax.tree_util.keystr(kp)))
    aligned = len(names_by_leaf) == len(flat)

    leaf_infos: List[LeafInfo] = []
    for i, (kp, info) in enumerate(flat):
        arg, sub = (names_by_leaf[i] if aligned
                    else (jax.tree_util.keystr(kp), ""))
        leaf_infos.append(LeafInfo(
            arg=arg, keypath=sub,
            aval=AvalInfo(shape=tuple(int(d) for d in info.shape),
                          dtype=str(info.dtype)),
            donated=bool(info.donated),
            aliased=(i in aliased_pos) if attribute else None))

    path, line = anchor_of(prog.anchor)
    return ProgramContext(
        name=prog.name, path=path, line=line,
        primitives=[PrimitiveUse(n, c)
                    for n, c in sorted(prim_counts.items())],
        out_avals=[_aval_info(a) for a in closed.out_avals],
        var_avals=var_avals,
        converts=converts,
        consts=consts,
        leaves=leaf_infos,
        declared_donate=tuple(prog.declared_donate),
        dynamic_arg_names=tuple(n for n, _ in prog.dynamic_args),
        while_carries=while_carries,
        alias_count=len(aliased_pos),
        donated_leaf_count=sum(1 for l in leaf_infos if l.donated),
    )


def _normalise_spec(spec) -> Tuple[Tuple[str, ...], ...]:
    """PartitionSpec -> per-dim tuples of axis names (empty = unsharded)."""
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(str(e) for e in entry))
        else:
            out.append((str(entry),))
    return tuple(out)


def build_contract_context(canonical_dims: dict,
                           mesh_extents: dict) -> ContractContext:
    """The layout contract, resolved to concrete shapes + extents.

    ``canonical_dims`` maps the symbolic dim names of
    ``layout.contract_entries`` ("cells"/"loci"/"P"/"K1"/"L") to the
    registry's canonical sizes; ``mesh_extents`` maps mesh axis names to
    shard counts (the 4x2 parity-mesh default lives in ``entrypoints``).
    """
    import inspect as _inspect

    from scdna_replication_tools_tpu import layout
    from scdna_replication_tools_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.abstract_mesh(mesh_extents.get(layout.CELLS_AXIS, 1),
                                  mesh_extents.get(layout.LOCI_AXIS, 1))
    factory_lines = {}
    rows: List[ContractRow] = []
    for entry in layout.contract_entries(mesh):
        if entry.factory not in factory_lines:
            fn = getattr(layout, entry.factory)
            factory_lines[entry.factory] = \
                _inspect.getsourcelines(fn)[1]
        shape = tuple(canonical_dims[d] for d in entry.dims)
        rows.append(ContractRow(
            tensor=entry.tensor, factory=entry.factory,
            spec=_normalise_spec(entry.spec),
            spec_rank=len(tuple(entry.spec)),
            shape=shape,
            line=factory_lines[entry.factory]))
    return ContractContext(
        path=repo_relpath(_inspect.getsourcefile(layout)),
        axis_extents=dict(mesh_extents), rows=rows)
