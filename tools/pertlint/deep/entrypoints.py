"""Registry of the package's real jit entry points, on canonical shapes.

Each entry declares how to build ONE production program on abstract
inputs: the jitted callable, the full positional/keyword arguments
(statics included, dynamics as ``jax.ShapeDtypeStruct``), the dynamic
argument names in positional order, and the donation the source
declares.  The deep engine traces and lowers every entry — nothing
executes — and runs DP001..DP005 over the results.

Canonical geometry: small enough to trace in milliseconds, shaped like
production — P=13 enumeration states, K=4 GC polynomial, and a cell/loci
grid divisible by the 4x2 cells-x-loci parity mesh (MULTICHIP dryrun),
so the same numbers anchor the DP006/DP007 divisibility checks.

The two placement entries need 8 local devices (the forced-host CPU
backend provides them; ``engine._ensure_cpu_devices`` sets the flag when
the backend is not yet initialised).  When fewer devices exist they are
skipped with a note rather than failing the gate — every other entry
still runs on one device.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Tuple

# one source of truth for the canonical trace geometry
CANONICAL_DIMS: Dict[str, int] = {
    "cells": 8,
    "loci": 16,
    "P": 13,
    "K1": 5,   # K + 1 GC-polynomial features
    "Kb": 4,   # ceil(log2 P) binary logit planes (enum_impl='binary')
    "L": 1,
}
MESH_EXTENTS: Dict[str, int] = {"cells": 4, "loci": 2}

MAX_ITER = 120
MIN_ITER = 10
DIAG_EVERY = 8
LEARNING_RATE = 0.05
B1, B2 = 0.8, 0.99


class SkipEntry(RuntimeError):
    """Raised by a builder when its prerequisites are absent (devices)."""


@dataclasses.dataclass
class EntryProgram:
    """One buildable entry point, ready for ``trace.build_program_context``."""
    name: str
    anchor: object                 # python callable anchoring path/line
    jit_fn: object                 # the jit-wrapped callable
    args: tuple                    # full positional args (statics included)
    kwargs: dict
    dynamic_args: List[Tuple[str, object]]  # (name, value), positional order
    declared_donate: Tuple[str, ...]


def _model_pieces():
    import jax

    from scdna_replication_tools_tpu.models.pert import (
        PertBatch,
        PertModelSpec,
        init_params,
    )

    spec = PertModelSpec(P=CANONICAL_DIMS["P"], K=CANONICAL_DIMS["K1"] - 1,
                         L=CANONICAL_DIMS["L"])
    batch = PertBatch.abstract(spec, CANONICAL_DIMS["cells"],
                               CANONICAL_DIMS["loci"])
    fixed: dict = {}
    params = jax.eval_shape(functools.partial(init_params, spec), batch,
                            fixed)
    return spec, batch, fixed, params


def _loss_fn(spec):
    from scdna_replication_tools_tpu.infer.runner import _PertLossFn

    return _PertLossFn(spec=spec)


def build_loss() -> EntryProgram:
    """The bare SVI objective: ``pert_loss`` via the runner's
    value-hashable loss callable — the program differentiated inside
    every fit."""
    import jax

    spec, batch, fixed, params = _model_pieces()
    loss = _loss_fn(spec)
    dynamic = [("params", params), ("fixed", fixed), ("batch", batch)]
    return EntryProgram(name="loss", anchor=type(loss).__call__,
                        jit_fn=jax.jit(loss),
                        args=(params, fixed, batch), kwargs={},
                        dynamic_args=dynamic, declared_donate=())


def _fit_common():
    import jax
    import jax.numpy as jnp

    from scdna_replication_tools_tpu.infer import svi

    spec, batch, fixed, params = _model_pieces()
    opt_state = jax.eval_shape(svi.make_opt_state, params)
    S = jax.ShapeDtypeStruct
    losses0 = S((MAX_ITER,), jnp.float32)
    diag0 = S((svi.DIAG_RING, 3), jnp.float32)
    i32 = S((), jnp.int32)
    f32 = S((), jnp.float32)
    loss_args = (fixed, batch)
    return svi, _loss_fn(spec), params, opt_state, losses0, diag0, i32, \
        f32, loss_args


def build_fit() -> EntryProgram:
    """The whole-budget fit program (``_run_fit``): one ``lax.while_loop``
    per fit, every init buffer donated."""
    (svi, loss, params, opt_state, losses0, diag0, i32, f32,
     loss_args) = _fit_common()
    args = (loss, params, opt_state, losses0, diag0, i32, loss_args,
            MAX_ITER, MIN_ITER, f32, LEARNING_RATE, B1, B2, DIAG_EVERY)
    dynamic = [("params0", params), ("opt_state0", opt_state),
               ("losses0", losses0), ("diag0", diag0), ("i0", i32),
               ("loss_args", loss_args), ("rel_tol", f32)]
    return EntryProgram(name="fit", anchor=svi._run_fit,
                        jit_fn=svi._run_fit, args=args, kwargs={},
                        dynamic_args=dynamic,
                        declared_donate=svi.FIT_DONATE_ARGNAMES)


def build_fit_chunk() -> EntryProgram:
    """The adaptive controller's chunk program (``_run_fit_chunk``):
    dynamic bound/tolerances, consumed-on-entry carries donated,
    ``params0`` deliberately NOT (the host keeps it as the best-loss
    checkpoint — the documented exception DP003 baselines)."""
    (svi, loss, params, opt_state, losses0, diag0, i32, f32,
     loss_args) = _fit_common()
    args = (loss, params, opt_state, losses0, diag0, i32, i32, i32, f32,
            f32, loss_args, min(9, MAX_ITER), B1, B2, DIAG_EVERY)
    dynamic = [("params0", params), ("opt_state0", opt_state),
               ("losses0", losses0), ("diag0", diag0), ("i0", i32),
               ("stop", i32), ("min_iter", i32), ("rel_tol", f32),
               ("lr", f32), ("loss_args", loss_args)]
    return EntryProgram(name="fit_chunk", anchor=svi._run_fit_chunk,
                        jit_fn=svi._run_fit_chunk, args=args, kwargs={},
                        dynamic_args=dynamic,
                        declared_donate=svi.CHUNK_DONATE_ARGNAMES)


def build_decode_slab() -> EntryProgram:
    """One compiled decode pass with the posterior-confidence maps on —
    the packaging/QC hot program."""
    from scdna_replication_tools_tpu.models import pert

    spec, batch, fixed, params = _model_pieces()
    args = (spec, params, fixed, batch)
    dynamic = [("params", params), ("fixed", fixed), ("batch", batch)]
    return EntryProgram(name="decode_slab", anchor=pert._decode_slab,
                        jit_fn=pert._decode_slab, args=args,
                        kwargs={"want_entropy": True},
                        dynamic_args=dynamic, declared_donate=())


def _binary_model_pieces():
    """The step-2 production shape under the independent-binary CN
    encoding (enum_impl='binary'): sparse one-hot prior, conditioned
    beta_means, fixed lambda — the spec the runner builds for an
    enumerated step, with the interpreter backend so the Pallas kernel
    traces/lowers on the CPU engine."""
    import jax
    import jax.numpy as jnp

    from scdna_replication_tools_tpu.models.pert import (
        PertBatch,
        PertModelSpec,
        init_params,
    )

    spec = PertModelSpec(P=CANONICAL_DIMS["P"], K=CANONICAL_DIMS["K1"] - 1,
                         L=CANONICAL_DIMS["L"], tau_mode="param",
                         cond_beta_means=True, fixed_lamb=True,
                         sparse_etas=True, enum_impl="binary_interpret")
    batch = PertBatch.abstract(spec, CANONICAL_DIMS["cells"],
                               CANONICAL_DIMS["loci"])
    S = jax.ShapeDtypeStruct
    fixed = {"beta_means": S((CANONICAL_DIMS["L"], CANONICAL_DIMS["K1"]),
                             jnp.float32),
             "lamb": S((), jnp.float32)}
    params = jax.eval_shape(functools.partial(init_params, spec), batch,
                            fixed)
    return spec, batch, fixed, params


def build_fit_chunk_binary() -> EntryProgram:
    """The controller chunk program under the binary CN encoding + the
    fused single-sweep Adam update (XLA implementation — the Pallas
    Adam kernel shares its math and is parity-pinned separately): the
    pi parameter is the Kb-plane ``pi_bin_logits`` and the optimizer
    update is one fused sweep per leaf.  Same donation contract as
    ``fit_chunk`` (params0 deliberately kept — DP003 baseline)."""
    import jax
    import jax.numpy as jnp

    from scdna_replication_tools_tpu.infer import svi

    spec, batch, fixed, params = _binary_model_pieces()
    opt_state = jax.eval_shape(svi.make_opt_state, params)
    S = jax.ShapeDtypeStruct
    losses0 = S((MAX_ITER,), jnp.float32)
    diag0 = S((svi.DIAG_RING, 3), jnp.float32)
    i32 = S((), jnp.int32)
    f32 = S((), jnp.float32)
    loss_args = (fixed, batch)
    loss = _loss_fn(spec)
    args = (loss, params, opt_state, losses0, diag0, i32, i32, i32, f32,
            f32, loss_args, min(9, MAX_ITER), B1, B2, DIAG_EVERY, "xla",
            "float32")
    dynamic = [("params0", params), ("opt_state0", opt_state),
               ("losses0", losses0), ("diag0", diag0), ("i0", i32),
               ("stop", i32), ("min_iter", i32), ("rel_tol", f32),
               ("lr", f32), ("loss_args", loss_args)]
    return EntryProgram(name="fit_chunk_binary",
                        anchor=svi._run_fit_chunk,
                        jit_fn=svi._run_fit_chunk, args=args, kwargs={},
                        dynamic_args=dynamic,
                        declared_donate=svi.CHUNK_DONATE_ARGNAMES)


def build_decode_slab_binary() -> EntryProgram:
    """The decode/QC slab under the binary CN encoding: the per-state
    log-pi tensor is expanded from the Kb planes inside the program
    (models.pert.binary_log_pi) — pure XLA, so it traces on any
    backend."""
    from scdna_replication_tools_tpu.models import pert

    spec, batch, fixed, params = _binary_model_pieces()
    args = (spec, params, fixed, batch)
    dynamic = [("params", params), ("fixed", fixed), ("batch", batch)]
    return EntryProgram(name="decode_slab_binary",
                        anchor=pert._decode_slab,
                        jit_fn=pert._decode_slab, args=args,
                        kwargs={"want_entropy": True},
                        dynamic_args=dynamic, declared_donate=())


def build_ppc() -> EntryProgram:
    """The posterior-predictive-check slab (``_ppc_slab``)."""
    import jax
    import jax.numpy as jnp

    from scdna_replication_tools_tpu.models import pert

    spec, batch, fixed, params = _model_pieces()
    S = jax.ShapeDtypeStruct
    bins = (CANONICAL_DIMS["cells"], CANONICAL_DIMS["loci"])
    cn_map = S(bins, jnp.int32)
    rep_map = S(bins, jnp.int32)
    key = S((2,), jnp.uint32)
    args = (spec, params, fixed, batch, cn_map, rep_map, key)
    dynamic = [("params", params), ("fixed", fixed), ("batch", batch),
               ("cn_map", cn_map), ("rep_map", rep_map), ("key", key)]
    return EntryProgram(name="ppc", anchor=pert._ppc_slab,
                        jit_fn=pert._ppc_slab, args=args,
                        kwargs={"num_replicates": 4},
                        dynamic_args=dynamic, declared_donate=())


def _placement_entry(name: str, anchor, specs: dict,
                     values: dict) -> EntryProgram:
    """A jit identity whose out_shardings place ``values`` per ``specs``
    on the canonical mesh — the traced form of ``shard_batch`` /
    ``shard_params``.  Lowering this program is what verifies the specs
    are consistent with the declared ranks on a real mesh (XLA rejects a
    rank-overflowing or unknown-axis sharding at lowering)."""
    import jax
    from jax.sharding import NamedSharding

    from scdna_replication_tools_tpu.parallel.mesh import make_mesh

    needed = MESH_EXTENTS["cells"] * MESH_EXTENTS["loci"]
    if len(jax.devices()) < needed:
        raise SkipEntry(f"{name}: needs {needed} devices, "
                        f"{len(jax.devices())} available")
    mesh = make_mesh(MESH_EXTENTS["cells"],
                     loci_shards=MESH_EXTENTS["loci"])
    shardings = {k: NamedSharding(mesh, specs[k]) for k in values}
    jit_fn = jax.jit(lambda tree: tree, out_shardings=shardings)
    return EntryProgram(name=name, anchor=anchor, jit_fn=jit_fn,
                        args=(values,), kwargs={},
                        dynamic_args=[("tree", values)],
                        declared_donate=())


def build_sharded_batch() -> EntryProgram:
    """Batch placement on the 4x2 mesh: every present PertBatch field
    against its ``layout.batch_specs`` PartitionSpec."""
    from scdna_replication_tools_tpu import layout
    from scdna_replication_tools_tpu.parallel import mesh as mesh_mod

    spec, batch, fixed, params = _model_pieces()
    specs = layout.batch_specs(layout.LOCI_AXIS)
    values = {name: getattr(batch, name) for name in specs
              if getattr(batch, name) is not None}
    return _placement_entry("sharded_batch", mesh_mod.shard_batch, specs,
                            values)


def build_sharded_params() -> EntryProgram:
    """Parameter placement on the 4x2 mesh: the full unconstrained
    parameter pytree against ``layout.param_specs``."""
    from scdna_replication_tools_tpu import layout
    from scdna_replication_tools_tpu.parallel import mesh as mesh_mod

    spec, batch, fixed, params = _model_pieces()
    specs = layout.param_specs(layout.LOCI_AXIS)
    return _placement_entry("sharded_params", mesh_mod.shard_params,
                            specs, dict(params))


REGISTRY: Dict[str, Callable[[], EntryProgram]] = {
    "loss": build_loss,
    "fit": build_fit,
    "fit_chunk": build_fit_chunk,
    "fit_chunk_binary": build_fit_chunk_binary,
    "decode_slab": build_decode_slab,
    "decode_slab_binary": build_decode_slab_binary,
    "ppc": build_ppc,
    "sharded_batch": build_sharded_batch,
    "sharded_params": build_sharded_params,
}


def build_all(names: Optional[List[str]] = None
              ) -> Tuple[List[EntryProgram], List[str]]:
    """Build every (or the named) registered entry -> (built, skipped).

    ``skipped`` carries human-readable reasons (currently only missing
    devices for the placement entries); build ERRORS propagate — a gate
    that cannot trace its programs must fail loudly, not shrink.
    """
    built: List[EntryProgram] = []
    skipped: List[str] = []
    for name, builder in REGISTRY.items():
        if names is not None and name not in names:
            continue
        try:
            built.append(builder())
        except SkipEntry as exc:
            skipped.append(str(exc))
    return built, skipped
