"""pertlint: JAX/TPU-aware static analysis for the PERT port.

The Pyro reference only needed a ``cuda`` flag; the TPU path depends on
invariants XLA never checks for us — no host syncs inside compiled
loops, no Python control flow on tracers, shardings owned by
``layout.py``, f32-stable dtypes in the enumeration kernel.  pertlint
encodes each invariant as an AST rule (PL001..PL006) and gates CI:

    python -m tools.pertlint scdna_replication_tools_tpu

exits non-zero on any violation that is neither inline-suppressed
(``# pertlint: disable=RULE``) nor grandfathered in the checked-in
baseline (``tools/pertlint/baseline.json``).

Pure stdlib (``ast`` + ``tokenize``): importable and runnable with no
jax/numpy installed, so the CI lint job stays seconds-fast.
"""

from tools.pertlint.core import Finding, Rule, all_rules  # noqa: F401
from tools.pertlint.engine import lint_paths, lint_source  # noqa: F401

__version__ = "0.1.0"
