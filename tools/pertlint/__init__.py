"""pertlint: JAX/TPU-aware static analysis for the PERT port.

The Pyro reference only needed a ``cuda`` flag; the TPU path depends on
invariants XLA never checks for us — no host syncs inside compiled
loops, no Python control flow on tracers, shardings owned by
``layout.py``, f32-stable dtypes in the enumeration kernel.  pertlint
encodes each invariant in one of two layers and gates CI:

    python -m tools.pertlint scdna_replication_tools_tpu   # AST (PLnnn)
    python -m tools.pertlint --deep                        # deep (DPnnn)

The AST layer lints source text and is pure stdlib (``ast`` +
``tokenize``) — importable and runnable with no jax/numpy installed, so
the fast path of the CI lint job stays seconds-fast.  The deep layer
(``tools/pertlint/deep``) traces the package's real jit entry points on
abstract inputs and audits the jaxprs, the lowered modules and the
tensor-layout contract.  Both exit non-zero on any violation that is
neither inline-suppressed (``# pertlint: disable=RULE``) nor
grandfathered in the checked-in baseline
(``tools/pertlint/baseline.json``).
"""

from tools.pertlint.core import Finding, Rule, all_rules  # noqa: F401
from tools.pertlint.engine import lint_paths, lint_source  # noqa: F401

__version__ = "0.1.0"
