import sys

from tools.pertlint.cli import main

sys.exit(main())
