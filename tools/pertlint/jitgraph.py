"""Module-local traced-code reachability for the jit-aware rules.

PL001/PL002 only make sense inside code that XLA traces.  This module
computes, per file, the set of function nodes that are *traced-reachable*:

* functions decorated with ``jax.jit`` / ``jit`` / ``pjit`` /
  ``shard_map`` — directly or via ``functools.partial(jax.jit, ...)``;
* functions passed to a jit/shard_map call expression
  (``fn = jax.jit(step)``, ``shard_map(kernel, mesh, ...)``);
* functions lexically nested inside a traced function (``cond``/``body``
  closures of ``lax.while_loop`` etc.);
* fixpoint closure over same-module calls: a plain function called by
  name from a traced function body is traced too.

The analysis is deliberately module-local — cross-module call graphs
buy little here (the package's jit entry points wrap same-module helpers)
and would make the tool's verdicts hard to predict for a reader of one
file.  ``static_argnames`` AND ``static_argnums`` of the jit decoration
are recorded (argnums resolved against the wrapped function's positional
parameter list) so rules can exempt Python-level arguments
(``float(max_iter)`` is not a sync, whether the argument is static by
name or by position).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)

_JIT_NAMES = {"jit", "pjit"}
_SHARD_NAMES = {"shard_map"}
_WRAPPER_NAMES = _JIT_NAMES | _SHARD_NAMES


def _tail_name(expr: ast.AST) -> Optional[str]:
    """'jit' for ``jit`` / ``jax.jit`` / ``jax.experimental.pjit.pjit``."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def is_wrapper_expr(expr: ast.AST) -> bool:
    """Is ``expr`` (not a call) a jit/shard_map callable reference?"""
    return _tail_name(expr) in _WRAPPER_NAMES


def _static_argnames(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value,
                                                                 str):
                    names.add(node.value)
    return names


def _static_argnums(call: ast.Call) -> Set[int]:
    """Integer positions of ``static_argnums`` (single int or tuple)."""
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, int) \
                        and not isinstance(node.value, bool):
                    nums.add(node.value)
    return nums


def _wrapper_call_info(call: ast.Call) -> Optional[Tuple[Set[str], Set[int]]]:
    """If ``call`` builds a jit/shard_map wrapper, its static arguments
    as ``(static_argnames, static_argnums)``.

    Matches ``jax.jit(...)``, ``shard_map(...)`` and the decorator-factory
    spelling ``functools.partial(jax.jit, ...)``.  Returns None when the
    call is unrelated.  ``static_argnums`` are positional indices; the
    caller resolves them against the wrapped function's parameter list
    (``resolve_static_argnums``) so positionally-static args get the same
    exemption as named ones.
    """
    if is_wrapper_expr(call.func):
        return _static_argnames(call), _static_argnums(call)
    if _tail_name(call.func) == "partial" and call.args \
            and is_wrapper_expr(call.args[0]):
        return _static_argnames(call), _static_argnums(call)
    return None


def positional_param_names(func: ast.AST) -> List[str]:
    """The wrapped function's positional parameters, in argnum order."""
    a = func.args
    return [arg.arg for arg in list(a.posonlyargs) + list(a.args)]


def resolve_static_argnums(func: ast.AST, nums: Set[int]) -> Set[str]:
    """Map ``static_argnums`` positions onto ``func``'s parameter names.

    Out-of-range (and negative) indices resolve to nothing — a jit with a
    bad argnum fails at runtime anyway, and guessing would silently
    exempt the wrong parameter.
    """
    names = positional_param_names(func)
    return {names[i] for i in nums if 0 <= i < len(names)}


@dataclasses.dataclass
class TracedInfo:
    """Per-file result: traced function nodes + their static argnames."""
    traced: Set[ast.AST]                      # FunctionDef nodes
    static_names: Dict[ast.AST, Set[str]]     # node -> static_argnames

    def statics_for(self, func: ast.AST) -> Set[str]:
        return self.static_names.get(func, set())


def _collect_functions(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree) if isinstance(n, FuncNode)]


def _local_bindings(func: ast.AST) -> Set[str]:
    """Names bound locally anywhere inside ``func``: parameters,
    assignment/loop/with/walrus/except targets, imports, nested defs."""
    bound: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                     (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, ast.arg):
            bound.add(node.arg)
        elif isinstance(node, FuncNode + (ast.ClassDef,)) and node is not func:
            bound.add(node.name)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                bound.add((a.asname or a.name).split(".")[0])
    return bound


def _called_names(func: ast.AST) -> Set[str]:
    """Bare names this function's body calls or references.

    References (not just calls) count: a function handed onwards
    (``lax.scan(body, ...)``, ``jax.vmap(f)``) is traced without a
    direct call expression.  Locally BOUND names are excluded — a local
    ``report = x * 2`` shadows any same-named module function, and
    letting it taint that function as traced produced false PL001
    positives on host-only helpers.
    """
    loads: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.add(node.id)
    return loads - _local_bindings(func)


def compute_traced(tree: ast.Module) -> TracedInfo:
    funcs = _collect_functions(tree)
    by_name: Dict[str, List[ast.AST]] = {}
    for f in funcs:
        by_name.setdefault(f.name, []).append(f)

    traced: Set[ast.AST] = set()
    static_names: Dict[ast.AST, Set[str]] = {}

    # 1) decorated entry points
    for f in funcs:
        for dec in f.decorator_list:
            statics = None
            if is_wrapper_expr(dec):
                statics = (set(), set())
            elif isinstance(dec, ast.Call):
                statics = _wrapper_call_info(dec)
            if statics is not None:
                names, nums = statics
                traced.add(f)
                static_names.setdefault(f, set()).update(
                    names | resolve_static_argnums(f, nums))

    # 2) call-site wrapping: jax.jit(f) / shard_map(f, ...) anywhere
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        statics = _wrapper_call_info(node)
        if statics is None:
            continue
        names, nums = statics
        for arg in node.args:
            name = arg.id if isinstance(arg, ast.Name) else None
            for f in by_name.get(name, []):
                traced.add(f)
                static_names.setdefault(f, set()).update(
                    names | resolve_static_argnums(f, nums))

    # 3) lexical nesting: functions defined inside a traced function
    #    (iterate until stable; nesting can be several levels deep)
    # 4) same-module call closure: names referenced from a traced body
    changed = True
    while changed:
        changed = False
        for f in list(traced):
            inherited = static_names.get(f, set())
            for inner in ast.walk(f):
                if isinstance(inner, FuncNode) and inner is not f \
                        and inner not in traced:
                    traced.add(inner)
                    static_names.setdefault(inner, set()).update(inherited)
                    changed = True
            for name in _called_names(f):
                for g in by_name.get(name, []):
                    if g not in traced:
                        traced.add(g)
                        changed = True
    return TracedInfo(traced=traced, static_names=static_names)


def owned_statements(func: ast.AST) -> List[ast.AST]:
    """Nodes of ``func``'s body excluding nested function bodies.

    Lets a rule visit each traced function exactly once even when its
    closures are independently in the traced set.
    """
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(func.body)
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FuncNode):
                continue
            stack.append(child)
    return out


def numpy_aliases(tree: ast.Module) -> Set[str]:
    """Names bound to the numpy module by imports (usually {'np'})."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


def jnp_aliases(tree: ast.Module) -> Set[str]:
    """Names bound to jax.numpy (usually {'jnp'})."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy" and a.asname:
                    aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
    return aliases


def lax_aliases(tree: ast.Module) -> Set[str]:
    """Names bound to jax.lax (usually {'lax'}); 'jax' itself also gives
    access via ``jax.lax`` attribute chains, handled by callers."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.lax" and a.asname:
                    aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "lax":
                        aliases.add(a.asname or "lax")
    return aliases


def root_name(expr: ast.AST) -> Optional[str]:
    """Leftmost name of an attribute chain: ``jnp`` for ``jnp.isnan``."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def attr_chain(expr: ast.AST) -> Tuple[str, ...]:
    """('jax', 'lax', 'cond') for ``jax.lax.cond``; () when not a chain."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return tuple(reversed(parts))
    return ()
