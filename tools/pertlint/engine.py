"""File walking, per-file rule execution, suppression + baseline filtering.

``lint_paths`` is the programmatic entry the CLI and the test gate share:
it returns a :class:`LintResult` whose ``new`` list is what gates the
build (error-severity findings that are neither suppressed inline nor
baselined).
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.pertlint import baseline as baseline_mod
from tools.pertlint import jitgraph, suppress
from tools.pertlint.core import Finding, Rule, all_rules

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build",
              "dist", ".eggs"}


@dataclasses.dataclass
class FileContext:
    """Everything a rule may need about one file; shared analyses cached."""
    path: str                 # as reported in findings (posix, as given)
    source: str
    lines: List[str]
    tree: ast.Module

    @functools.cached_property
    def traced(self) -> jitgraph.TracedInfo:
        return jitgraph.compute_traced(self.tree)

    @functools.cached_property
    def numpy_aliases(self) -> Set[str]:
        return jitgraph.numpy_aliases(self.tree)

    @functools.cached_property
    def jnp_aliases(self) -> Set[str]:
        return jitgraph.jnp_aliases(self.tree)

    @functools.cached_property
    def lax_aliases(self) -> Set[str]:
        return jitgraph.lax_aliases(self.tree)

    @functools.cached_property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        return {child: parent for parent in ast.walk(self.tree)
                for child in ast.iter_child_nodes(parent)}


@dataclasses.dataclass
class LintResult:
    new: List[Finding]                  # gate: not suppressed, not baselined
    baselined: List[Finding]
    suppressed: List[Finding]
    stale_baseline: Set[str]            # fingerprints no tree finding matches
    parse_errors: List[Tuple[str, str]]  # (path, message)
    files_checked: int = 0
    missing_files: List[str] = dataclasses.field(default_factory=list)
    # baseline entries pointing at files that no longer exist (dead
    # weight a lint run can never match) — the CLI warns on these

    @property
    def gating(self) -> List[Finding]:
        return [f for f in self.new if f.severity == "error"]

    def merge(self, other: "LintResult") -> "LintResult":
        """Combine two passes (the AST layer + the deep layer) into the
        single result the CLI reports and gates on."""
        return LintResult(
            new=self.new + other.new,
            baselined=self.baselined + other.baselined,
            suppressed=self.suppressed + other.suppressed,
            stale_baseline=self.stale_baseline | other.stale_baseline,
            parse_errors=self.parse_errors + other.parse_errors,
            files_checked=self.files_checked + other.files_checked,
            missing_files=sorted(set(self.missing_files)
                                 | set(other.missing_files)),
        )


def iter_python_files(paths: Sequence[str]) -> List[pathlib.Path]:
    out: List[pathlib.Path] = []
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in f.parts):
                    out.append(f)
    return out


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Sequence[Rule]] = None
                ) -> Tuple[List[Finding], List[Finding]]:
    """Lint one source blob -> (findings, suppressed).  Test seam."""
    rules = list(rules) if rules is not None else all_rules()
    tree = ast.parse(source, filename=path)
    ctx = FileContext(path=path, source=source,
                      lines=source.splitlines(), tree=tree)
    per_line, file_wide = suppress.parse_suppressions(source)
    kept: List[Finding] = []
    dropped: List[Finding] = []
    for rule in rules:
        for finding in rule.check(ctx):
            if suppress.is_suppressed(finding.rule, finding.line, per_line,
                                      file_wide):
                dropped.append(finding)
            else:
                kept.append(finding)
    key = lambda f: (f.line, f.col, f.rule)  # noqa: E731
    return sorted(set(kept), key=key), sorted(set(dropped), key=key)


def lint_paths(paths: Sequence[str],
               baseline_path: Optional[pathlib.Path] = None,
               rules: Optional[Sequence[Rule]] = None) -> LintResult:
    rules = list(rules) if rules is not None else all_rules()
    entries = baseline_mod.load_entries(baseline_path) if baseline_path \
        else []
    known = {e["fingerprint"] for e in entries}

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    parse_errors: List[Tuple[str, str]] = []
    sources: Dict[str, List[str]] = {}
    files = iter_python_files(paths)
    for f in files:
        path = f.as_posix()
        try:
            source = f.read_text()
            kept, dropped = lint_source(source, path=path, rules=rules)
        except (SyntaxError, UnicodeDecodeError) as exc:
            parse_errors.append((path, f"{type(exc).__name__}: {exc}"))
            continue
        sources[path] = source.splitlines()
        findings.extend(kept)
        suppressed.extend(dropped)

    fingerprinted = baseline_mod.fingerprint_findings(findings, sources)
    new = [f for f, fp in fingerprinted if fp not in known]
    baselined = [f for f, fp in fingerprinted if fp in known]
    # staleness is scoped to what this run could have produced: only
    # entries whose rule actually ran AND whose path was covered can be
    # declared stale — linting one file must not mark the rest of the
    # grandfathered debt stale, and an AST-only run must not flag the
    # deep (DP) layer's entries
    rule_ids = {r.id for r in rules}
    produced = {fp for _, fp in fingerprinted}
    stale = {e["fingerprint"] for e in entries
             if e["rule"] in rule_ids and _covered_by(e["path"], paths)
             and e["fingerprint"] not in produced}
    missing = sorted({e["path"] for e in baseline_mod.missing_file_entries(
        entries, baseline_path)})
    return LintResult(new=new, baselined=baselined, suppressed=suppressed,
                      stale_baseline=stale, parse_errors=parse_errors,
                      files_checked=len(files), missing_files=missing)


def _covered_by(entry_path: str, roots: Sequence[str]) -> bool:
    """Does ``entry_path`` fall under any of the snapshot roots?"""
    ep = pathlib.PurePosixPath(pathlib.Path(entry_path).as_posix())
    for raw in roots:
        rp = pathlib.PurePosixPath(pathlib.Path(raw).as_posix())
        if ep == rp or str(ep).startswith(str(rp).rstrip("/") + "/"):
            return True
    return False


def snapshot_baseline(paths: Sequence[str],
                      baseline_path: pathlib.Path,
                      rules: Optional[Sequence[Rule]] = None,
                      extra_fingerprinted: Optional[
                          List[Tuple[Finding, str]]] = None,
                      extra_rule_ids: Optional[Set[str]] = None) -> int:
    """Write the baseline from the tree's current findings; -> count.

    Entries for paths OUTSIDE ``paths`` — or produced by rules this run
    did not execute (the deep DP layer when only the AST pass ran) — are
    retained untouched, so a partial snapshot grandfathers new findings
    without silently dropping the rest of the debt.  Entries covered by
    the executed rules — path-scoped for the AST layer, program-scoped
    (path-independent) for the deep layer — are fully rebuilt: that is
    what prunes stale ones.  Rationales survive re-snapshotting (matched
    by fingerprint).  ``extra_fingerprinted``/``extra_rule_ids`` fold
    another pass's findings and its FULL executed-rule set (the deep
    layer's) into the same snapshot; the ids must come from the rule
    registry, not from the findings, or a deep rule that went clean
    would leave its stale entries behind.
    """
    rules = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    sources: Dict[str, List[str]] = {}
    for f in iter_python_files(paths):
        path = f.as_posix()
        try:
            source = f.read_text()
            kept, _ = lint_source(source, path=path, rules=rules)
        except (SyntaxError, UnicodeDecodeError):
            continue
        sources[path] = source.splitlines()
        findings.extend(kept)
    fingerprinted = baseline_mod.fingerprint_findings(findings, sources)
    fingerprinted += list(extra_fingerprinted or [])
    ast_rule_ids = {r.id for r in rules}
    deep_rule_ids = set(extra_rule_ids or ()) \
        | {f.rule for f, _ in (extra_fingerprinted or [])}
    prior = baseline_mod.load_entries(baseline_path)
    retained = [e for e in prior
                if e["rule"] not in deep_rule_ids
                and (e["rule"] not in ast_rule_ids
                     or not _covered_by(e["path"], paths))]
    baseline_mod.write(baseline_path, fingerprinted, retained,
                       keep_rationales=baseline_mod.rationales(prior))
    return len(fingerprinted) + len(retained)


def update_baseline(paths: Sequence[str],
                    baseline_path: pathlib.Path,
                    rules: Optional[Sequence[Rule]] = None,
                    extra_produced: Optional[Set[str]] = None,
                    extra_rule_ids: Optional[Set[str]] = None
                    ) -> Tuple[int, int]:
    """Prune-only baseline hygiene -> (kept, pruned).

    Drops entries that are (a) stale — their rule ran over their
    (covered) path and the fingerprint was not produced — or (b) dead —
    their file no longer exists on disk (whatever the path coverage: a
    deleted file can never match again).  NEVER adds entries, so new
    findings keep gating; rationales of surviving entries are untouched.
    ``extra_produced``/``extra_rule_ids`` fold in another pass's
    fingerprints (the deep layer's) so its entries are pruned by the
    same rule.
    """
    rules = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    sources: Dict[str, List[str]] = {}
    for f in iter_python_files(paths):
        path = f.as_posix()
        try:
            source = f.read_text()
            kept, _ = lint_source(source, path=path, rules=rules)
        except (SyntaxError, UnicodeDecodeError):
            continue
        sources[path] = source.splitlines()
        findings.extend(kept)
    produced = {fp for _, fp in
                baseline_mod.fingerprint_findings(findings, sources)}
    produced |= set(extra_produced or ())
    ast_rule_ids = {r.id for r in rules}
    deep_rule_ids = set(extra_rule_ids or ())

    entries = baseline_mod.load_entries(baseline_path)
    keep: List[dict] = []
    pruned = 0
    for e in entries:
        gone = e["fingerprint"] not in produced
        dead = not baseline_mod.entry_file_exists(e.get("path", ""),
                                                  baseline_path)
        # AST entries are path-scoped (only a covered path could have
        # re-produced them); deep entries are program-scoped — if the
        # deep rules ran at all, an unproduced entry is stale
        stale = gone and (
            (e["rule"] in ast_rule_ids and _covered_by(e["path"], paths))
            or e["rule"] in deep_rule_ids)
        if dead or stale:
            pruned += 1
        else:
            keep.append(e)
    baseline_mod.write(baseline_path, [], keep)
    return len(keep), pruned
