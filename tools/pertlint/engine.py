"""File walking, per-file rule execution, suppression + baseline filtering.

``lint_paths`` is the programmatic entry the CLI and the test gate share:
it returns a :class:`LintResult` whose ``new`` list is what gates the
build (error-severity findings that are neither suppressed inline nor
baselined).
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.pertlint import baseline as baseline_mod
from tools.pertlint import jitgraph, suppress
from tools.pertlint.core import Finding, Rule, all_rules

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build",
              "dist", ".eggs"}


@dataclasses.dataclass
class FileContext:
    """Everything a rule may need about one file; shared analyses cached."""
    path: str                 # as reported in findings (posix, as given)
    source: str
    lines: List[str]
    tree: ast.Module

    @functools.cached_property
    def traced(self) -> jitgraph.TracedInfo:
        return jitgraph.compute_traced(self.tree)

    @functools.cached_property
    def numpy_aliases(self) -> Set[str]:
        return jitgraph.numpy_aliases(self.tree)

    @functools.cached_property
    def jnp_aliases(self) -> Set[str]:
        return jitgraph.jnp_aliases(self.tree)

    @functools.cached_property
    def lax_aliases(self) -> Set[str]:
        return jitgraph.lax_aliases(self.tree)

    @functools.cached_property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        return {child: parent for parent in ast.walk(self.tree)
                for child in ast.iter_child_nodes(parent)}


@dataclasses.dataclass
class LintResult:
    new: List[Finding]                  # gate: not suppressed, not baselined
    baselined: List[Finding]
    suppressed: List[Finding]
    stale_baseline: Set[str]            # fingerprints no tree finding matches
    parse_errors: List[Tuple[str, str]]  # (path, message)
    files_checked: int = 0

    @property
    def gating(self) -> List[Finding]:
        return [f for f in self.new if f.severity == "error"]


def iter_python_files(paths: Sequence[str]) -> List[pathlib.Path]:
    out: List[pathlib.Path] = []
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in f.parts):
                    out.append(f)
    return out


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Sequence[Rule]] = None
                ) -> Tuple[List[Finding], List[Finding]]:
    """Lint one source blob -> (findings, suppressed).  Test seam."""
    rules = list(rules) if rules is not None else all_rules()
    tree = ast.parse(source, filename=path)
    ctx = FileContext(path=path, source=source,
                      lines=source.splitlines(), tree=tree)
    per_line, file_wide = suppress.parse_suppressions(source)
    kept: List[Finding] = []
    dropped: List[Finding] = []
    for rule in rules:
        for finding in rule.check(ctx):
            if suppress.is_suppressed(finding.rule, finding.line, per_line,
                                      file_wide):
                dropped.append(finding)
            else:
                kept.append(finding)
    key = lambda f: (f.line, f.col, f.rule)  # noqa: E731
    return sorted(set(kept), key=key), sorted(set(dropped), key=key)


def lint_paths(paths: Sequence[str],
               baseline_path: Optional[pathlib.Path] = None,
               rules: Optional[Sequence[Rule]] = None) -> LintResult:
    rules = list(rules) if rules is not None else all_rules()
    known = baseline_mod.load(baseline_path) if baseline_path else set()

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    parse_errors: List[Tuple[str, str]] = []
    sources: Dict[str, List[str]] = {}
    files = iter_python_files(paths)
    for f in files:
        path = f.as_posix()
        try:
            source = f.read_text()
            kept, dropped = lint_source(source, path=path, rules=rules)
        except (SyntaxError, UnicodeDecodeError) as exc:
            parse_errors.append((path, f"{type(exc).__name__}: {exc}"))
            continue
        sources[path] = source.splitlines()
        findings.extend(kept)
        suppressed.extend(dropped)

    fingerprinted = baseline_mod.fingerprint_findings(findings, sources)
    new = [f for f, fp in fingerprinted if fp not in known]
    baselined = [f for f, fp in fingerprinted if fp in known]
    stale = known - {fp for _, fp in fingerprinted}
    return LintResult(new=new, baselined=baselined, suppressed=suppressed,
                      stale_baseline=stale, parse_errors=parse_errors,
                      files_checked=len(files))


def _covered_by(entry_path: str, roots: Sequence[str]) -> bool:
    """Does ``entry_path`` fall under any of the snapshot roots?"""
    ep = pathlib.PurePosixPath(pathlib.Path(entry_path).as_posix())
    for raw in roots:
        rp = pathlib.PurePosixPath(pathlib.Path(raw).as_posix())
        if ep == rp or str(ep).startswith(str(rp).rstrip("/") + "/"):
            return True
    return False


def snapshot_baseline(paths: Sequence[str],
                      baseline_path: pathlib.Path,
                      rules: Optional[Sequence[Rule]] = None) -> int:
    """Write the baseline from the tree's current findings; -> count.

    Entries for paths OUTSIDE ``paths`` are retained untouched, so a
    partial-tree snapshot grandfathers new findings without silently
    dropping the rest of the debt (entries under ``paths`` are fully
    rebuilt — that is what prunes stale ones).
    """
    rules = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    sources: Dict[str, List[str]] = {}
    for f in iter_python_files(paths):
        path = f.as_posix()
        try:
            source = f.read_text()
            kept, _ = lint_source(source, path=path, rules=rules)
        except (SyntaxError, UnicodeDecodeError):
            continue
        sources[path] = source.splitlines()
        findings.extend(kept)
    fingerprinted = baseline_mod.fingerprint_findings(findings, sources)
    retained = [e for e in baseline_mod.load_entries(baseline_path)
                if not _covered_by(e["path"], paths)]
    baseline_mod.write(baseline_path, fingerprinted, retained)
    return len(fingerprinted) + len(retained)
