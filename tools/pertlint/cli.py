"""``python -m tools.pertlint`` — the CI gate.

Three analysis layers share one CLI, one baseline and one suppression
syntax: the stdlib AST layer (PLnnn rules, runs over the given paths),
the deep jaxpr/sharding layer (DPnnn rules, ``--deep``; traces the
registered jit entry points on abstract inputs — needs jax, no
devices), and the interprocedural flow layer (FLnnn rules, ``--flow``;
whole-package call-graph + config-to-jit dataflow — stdlib only, and
it also emits the ``PROGRAM_IDENTITY.json`` certificate).  Any
combination runs the requested layers and gates on the union.

Exit codes: 0 clean (no new error-severity findings), 1 new violations,
2 usage/parse errors.  ``--write-baseline`` snapshots the current
findings as grandfathered; ``--update-baseline`` only PRUNES stale/dead
entries (never grandfathers anything new); ``--no-baseline`` ignores
the baseline file (shows the whole debt).  ``--format=github`` renders
findings as GitHub Actions workflow annotations so CI failures mark up
the diff.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import List, Optional

from tools.pertlint.core import Finding, all_rules
from tools.pertlint.engine import (
    LintResult,
    lint_paths,
    snapshot_baseline,
    update_baseline,
)

DEFAULT_BASELINE = pathlib.Path(__file__).parent / "baseline.json"
DEFAULT_IDENTITY_OUT = pathlib.Path("artifacts") / "PROGRAM_IDENTITY.json"

_LAYERS = (("ast", "ast layer", ""),
           ("deep", "deep jaxpr/sharding layer", "--deep"),
           ("flow", "interprocedural flow layer", "--flow"))


def _list_rules() -> str:
    """Roster computed from the registry — counts can never go stale."""
    lines = []
    for kind, label, flag in _LAYERS:
        rules = all_rules(kind=kind)
        suffix = f", {flag}" if flag else ""
        lines.append(f"pertlint rules ({label}: {len(rules)} rules"
                     f"{suffix}):")
        for rule in rules:
            lines.append(f"  {rule.id}  {rule.name:<28} [{rule.severity}] "
                         f"{rule.description}")
    return "\n".join(lines)


def _github_annotation(f: Finding) -> str:
    level = "error" if f.severity == "error" else "warning"
    # '::' and newlines would terminate the annotation early
    message = f.message.replace("%", "%25").replace("\n", "%0A")
    return (f"::{level} file={f.path},line={f.line},col={f.col + 1},"
            f"title=pertlint {f.rule}::{message}")


def _warn(args, text: str) -> None:
    if args.format == "github":
        print(f"::warning title=pertlint::{text}")
    else:
        print(f"pertlint: warning: {text}", file=sys.stderr)


def _render(args, result: LintResult, deep_stats=None,
            flow_stats=None) -> None:
    if args.format == "json":
        payload = {
            "files_checked": result.files_checked,
            "new": [vars(f) for f in result.new],
            "baselined": len(result.baselined),
            "suppressed": len(result.suppressed),
            "stale_baseline": sorted(result.stale_baseline),
            "missing_files": result.missing_files,
            "parse_errors": result.parse_errors,
        }
        if deep_stats is not None:
            payload["deep"] = {
                "entrypoints": deep_stats.entrypoints,
                "skipped": deep_stats.skipped,
                "contract_rows": deep_stats.contract_rows,
                "unrationalized": deep_stats.unrationalized,
            }
        if flow_stats is not None:
            payload["flow"] = {
                "modules": flow_stats.modules,
                "functions": flow_stats.functions,
                "collective_bearing": flow_stats.collective_bearing,
                "entries": flow_stats.entries,
                "verdicts": flow_stats.verdicts,
                "unrationalized": flow_stats.unrationalized,
            }
        print(json.dumps(payload, indent=1))
        return

    for f in result.new:
        print(_github_annotation(f) if args.format == "github"
              else f.render())
    for path, msg in result.parse_errors:
        print(f"{path}:1:0: parse-error {msg}", file=sys.stderr)
    if result.stale_baseline:
        n = len(result.stale_baseline)
        _warn(args, f"{n} stale baseline entr{'ies' if n != 1 else 'y'} "
                    f"(fixed or edited) — run --update-baseline to prune")
    if result.missing_files:
        _warn(args, f"baseline references {len(result.missing_files)} "
                    f"missing file(s): {', '.join(result.missing_files)} — "
                    f"run --update-baseline to prune")
    if deep_stats is not None and deep_stats.unrationalized:
        _warn(args, f"{len(deep_stats.unrationalized)} baselined deep "
                    f"finding(s) lack a 'rationale' — semantic debt needs "
                    f"a recorded WHY (edit the baseline entries: "
                    f"{', '.join(deep_stats.unrationalized)})")
    if flow_stats is not None and flow_stats.unrationalized:
        _warn(args, f"{len(flow_stats.unrationalized)} baselined flow "
                    f"finding(s) lack a 'rationale' — semantic debt needs "
                    f"a recorded WHY (edit the baseline entries: "
                    f"{', '.join(flow_stats.unrationalized)})")
    gating = result.gating
    warnings = len(result.new) - len(gating)
    deep_note = ""
    if deep_stats is not None:
        deep_note = (f"; deep: {len(deep_stats.entrypoints)} entry points "
                     f"traced, {deep_stats.contract_rows} contract rows")
        if deep_stats.skipped:
            deep_note += f", {len(deep_stats.skipped)} skipped"
    flow_note = ""
    if flow_stats is not None:
        v = flow_stats.verdicts
        covered = sum(1 for x in v.values() if x == "covered")
        flow_note = (f"; flow: {flow_stats.functions} functions in "
                     f"{flow_stats.modules} modules, "
                     f"{len(flow_stats.entries)} entry points certified "
                     f"({covered}/{len(v)} hash-covered)")
    print(f"pertlint: {result.files_checked} files, "
          f"{len(gating)} new violation{'s' if len(gating) != 1 else ''}"
          + (f" + {warnings} warning{'s' if warnings != 1 else ''}"
             if warnings else "")
          + f" ({len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed)"
          + deep_note + flow_note)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.pertlint",
        description="JAX/TPU-aware static analysis for the PERT port "
                    "(see tools/pertlint/README.md)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to lint with the AST layer "
                         "(e.g. scdna_replication_tools_tpu); may be empty "
                         "with --deep")
    ap.add_argument("--deep", action="store_true",
                    help="also run the deep jaxpr/sharding layer "
                         "(DP rules; traces the registered jit entry "
                         "points on abstract inputs — needs jax, CPU only)")
    ap.add_argument("--flow", action="store_true",
                    help="also run the interprocedural flow layer "
                         "(FL rules; whole-package call graph + "
                         "config-to-jit dataflow — stdlib only, nothing "
                         "is imported or traced) and write the "
                         "program-identity certificate")
    ap.add_argument("--identity-out", type=pathlib.Path,
                    default=DEFAULT_IDENTITY_OUT,
                    help="where --flow writes PROGRAM_IDENTITY.json "
                         "(default: %(default)s; '-' to skip writing)")
    ap.add_argument("--baseline", type=pathlib.Path,
                    default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings "
                         "(default: %(default)s; missing file = empty)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report the full debt")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into --baseline and "
                         "exit 0 (rationales survive by fingerprint)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="prune stale/dead baseline entries and exit 0 — "
                         "never grandfathers new findings")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--format", choices=["text", "json", "github"],
                    default="text",
                    help="github = GitHub Actions ::error/::warning "
                         "annotations (CI marks up the diff)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if not args.paths and not args.deep and not args.flow:
        ap.print_usage(sys.stderr)
        print("error: no paths given (and neither --deep nor --flow "
              "requested)", file=sys.stderr)
        return 2
    if args.write_baseline and args.update_baseline:
        print("error: --write-baseline and --update-baseline are "
              "mutually exclusive", file=sys.stderr)
        return 2

    ast_rules = all_rules(kind="ast")
    deep_ids = {r.id for r in all_rules(kind="deep")}
    flow_ids = {r.id for r in all_rules(kind="flow")}
    deep_select = flow_select = None
    if args.select:
        wanted = {r.strip() for r in args.select.split(",") if r.strip()}
        known = {r.id for r in all_rules(kind=None)}
        unknown = wanted - known
        if unknown:
            print(f"error: unknown rule ids {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        if (wanted & deep_ids) and not args.deep:
            # without --deep the deep layer never runs: exiting 0 here
            # would be a silent false-clean on the selected DP rules
            print(f"error: selected deep rule(s) "
                  f"{sorted(wanted & deep_ids)} require --deep",
                  file=sys.stderr)
            return 2
        if (wanted & flow_ids) and not args.flow:
            print(f"error: selected flow rule(s) "
                  f"{sorted(wanted & flow_ids)} require --flow",
                  file=sys.stderr)
            return 2
        ast_rules = [r for r in ast_rules if r.id in wanted]
        deep_select = flow_select = wanted

    baseline = None if args.no_baseline else args.baseline
    deep_result = deep_stats = None
    deep_fingerprinted = []
    if args.deep:
        from tools.pertlint.deep.engine import deep_lint

        deep_result, deep_stats, deep_fingerprinted = deep_lint(
            select=deep_select, baseline_path=baseline)

    flow_result = flow_stats = None
    flow_fingerprinted = []
    if args.flow:
        from tools.pertlint.flow.engine import flow_lint

        flow_result, flow_stats, flow_fingerprinted = flow_lint(
            select=flow_select, baseline_path=baseline)
        if str(args.identity_out) != "-" and flow_stats.entries:
            args.identity_out.parent.mkdir(parents=True, exist_ok=True)
            args.identity_out.write_text(
                json.dumps(flow_stats.identity_report, indent=1,
                           sort_keys=False) + "\n")

    extra_fingerprinted = deep_fingerprinted + flow_fingerprinted
    extra_rule_ids = (deep_ids if args.deep else set()) \
        | (flow_ids if args.flow else set())

    if args.write_baseline:
        if args.select:
            # a rule-subset snapshot would rebuild the covered paths'
            # entries with the unselected rules' findings dropped —
            # silent baseline data loss; snapshot with the full rule set
            print("error: --write-baseline cannot be combined with "
                  "--select (it would drop the unselected rules' "
                  "grandfathered entries)", file=sys.stderr)
            return 2
        n = snapshot_baseline(args.paths, args.baseline, rules=ast_rules,
                              extra_fingerprinted=extra_fingerprinted,
                              extra_rule_ids=extra_rule_ids)
        print(f"pertlint: baseline written to {args.baseline} "
              f"({n} grandfathered finding{'s' if n != 1 else ''}; "
              f"entries outside the given paths/rules retained)")
        if extra_fingerprinted:
            print("pertlint: note: add a one-line 'rationale' to every "
                  "new DP/FL entry — semantic debt without a WHY does "
                  "not pass review")
        return 0

    if args.update_baseline:
        extra_produced = {fp for _, fp in extra_fingerprinted}
        # only the deep/flow rules that actually RAN may prune entries
        prunable = set()
        if args.deep:
            prunable |= (deep_ids & deep_select if deep_select
                         else deep_ids)
        if args.flow:
            prunable |= (flow_ids & flow_select if flow_select
                         else flow_ids)
        kept, pruned = update_baseline(
            args.paths, args.baseline, rules=ast_rules,
            extra_produced=extra_produced, extra_rule_ids=prunable)
        print(f"pertlint: baseline updated — {kept} entries kept, "
              f"{pruned} stale/dead entr{'ies' if pruned != 1 else 'y'} "
              f"pruned")
        return 0

    result = LintResult(new=[], baselined=[], suppressed=[],
                        stale_baseline=set(), parse_errors=[])
    if args.paths:
        result = lint_paths(args.paths, baseline_path=baseline,
                            rules=ast_rules)
    if deep_result is not None:
        result = result.merge(deep_result)
    if flow_result is not None:
        result = result.merge(flow_result)

    _render(args, result, deep_stats, flow_stats)

    if result.parse_errors:
        return 2
    return 1 if result.gating else 0


if __name__ == "__main__":
    sys.exit(main())
