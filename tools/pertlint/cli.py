"""``python -m tools.pertlint`` — the CI gate.

Exit codes: 0 clean (no new error-severity findings), 1 new violations,
2 usage/parse errors.  ``--write-baseline`` snapshots the current
findings as grandfathered; ``--no-baseline`` ignores the baseline file
(shows the whole debt).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import List, Optional

from tools.pertlint.core import all_rules
from tools.pertlint.engine import lint_paths, snapshot_baseline

DEFAULT_BASELINE = pathlib.Path(__file__).parent / "baseline.json"


def _list_rules() -> str:
    lines = ["pertlint rules:"]
    for rule in all_rules():
        lines.append(f"  {rule.id}  {rule.name:<20} [{rule.severity}] "
                     f"{rule.description}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.pertlint",
        description="JAX/TPU-aware static analysis for the PERT port "
                    "(see tools/pertlint/README.md)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to lint "
                         "(e.g. scdna_replication_tools_tpu)")
    ap.add_argument("--baseline", type=pathlib.Path,
                    default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings "
                         "(default: %(default)s; missing file = empty)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report the full debt")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into --baseline and "
                         "exit 0")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--format", choices=["text", "json"], default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2

    rules = all_rules()
    if args.select:
        wanted = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"error: unknown rule ids {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    if args.write_baseline:
        if args.select:
            # a rule-subset snapshot would rebuild the covered paths'
            # entries with the unselected rules' findings dropped —
            # silent baseline data loss; snapshot with the full rule set
            print("error: --write-baseline cannot be combined with "
                  "--select (it would drop the unselected rules' "
                  "grandfathered entries)", file=sys.stderr)
            return 2
        n = snapshot_baseline(args.paths, args.baseline, rules=rules)
        print(f"pertlint: baseline written to {args.baseline} "
              f"({n} grandfathered finding{'s' if n != 1 else ''}; "
              f"entries outside the given paths retained)")
        return 0

    baseline = None if args.no_baseline else args.baseline
    result = lint_paths(args.paths, baseline_path=baseline, rules=rules)

    if args.format == "json":
        print(json.dumps({
            "files_checked": result.files_checked,
            "new": [vars(f) for f in result.new],
            "baselined": len(result.baselined),
            "suppressed": len(result.suppressed),
            "stale_baseline": sorted(result.stale_baseline),
            "parse_errors": result.parse_errors,
        }, indent=1))
    else:
        for f in result.new:
            print(f.render())
        for path, msg in result.parse_errors:
            print(f"{path}:1:0: parse-error {msg}", file=sys.stderr)
        if result.stale_baseline:
            print(f"pertlint: note: {len(result.stale_baseline)} stale "
                  f"baseline entr{'ies' if len(result.stale_baseline) != 1 else 'y'} "
                  f"(fixed or edited) — run --write-baseline to prune",
                  file=sys.stderr)
        gating = result.gating
        warnings = len(result.new) - len(gating)
        print(f"pertlint: {result.files_checked} files, "
              f"{len(gating)} new violation{'s' if len(gating) != 1 else ''}"
              + (f" + {warnings} warning{'s' if warnings != 1 else ''}"
                 if warnings else "")
              + f" ({len(result.baselined)} baselined, "
                f"{len(result.suppressed)} suppressed)")

    if result.parse_errors:
        return 2
    return 1 if result.gating else 0


if __name__ == "__main__":
    sys.exit(main())
