"""Baseline file: grandfathered findings that do not gate the build.

The baseline is a checked-in JSON list of finding fingerprints.  A
fingerprint is content-addressed — ``sha1(rule : path : stripped source
line : occurrence-index)`` — so it survives unrelated edits that shift
line numbers, and only breaks when the flagged line itself changes
(at which point the finding deserves a fresh look).

Workflow: ``python -m tools.pertlint <paths> --write-baseline`` snapshots
every current finding; subsequent runs report (and gate on) only
findings that are NOT in the snapshot.  Stale entries — fingerprints no
longer produced by the tree — are reported so the baseline shrinks as
debt is paid down; ``--write-baseline`` prunes them.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
from typing import Dict, Iterable, List, Set, Tuple

from tools.pertlint.core import Finding

BASELINE_VERSION = 1


def fingerprint(finding: Finding, line_text: str, occurrence: int) -> str:
    payload = f"{finding.rule}:{finding.path}:{line_text.strip()}:{occurrence}"
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


def fingerprint_findings(findings: Iterable[Finding],
                         sources: Dict[str, List[str]]
                         ) -> List[Tuple[Finding, str]]:
    """Pair each finding with its fingerprint.

    ``sources`` maps path -> source lines.  Identical flagged lines in
    the same file get distinct occurrence indices (in line order) so two
    copies of a violation need two baseline entries.
    """
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        lines = sources.get(f.path, [])
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        key = (f.rule, f.path, text.strip())
        occurrence = seen.get(key, 0)
        seen[key] = occurrence + 1
        out.append((f, fingerprint(f, text, occurrence)))
    return out


def load_entries(path: pathlib.Path) -> List[dict]:
    """Raw entry dicts of a baseline file; missing file = empty baseline."""
    if not path.is_file():
        return []
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: unsupported baseline version "
                         f"{data.get('version')!r}")
    return list(data.get("findings", []))


def load(path: pathlib.Path) -> Set[str]:
    """Fingerprint set of a baseline file; missing file = empty baseline."""
    return {e["fingerprint"] for e in load_entries(path)}


def write(path: pathlib.Path,
          fingerprinted: List[Tuple[Finding, str]],
          retained_entries: List[dict] = ()) -> None:
    """Write retained (out-of-scope) entries + the fresh snapshot.

    ``retained_entries`` are prior entries for paths NOT covered by the
    snapshot run — a partial-tree ``--write-baseline`` must not silently
    drop the rest of the grandfathered debt.
    """
    entries = list(retained_entries) + [
        {"rule": f.rule, "path": f.path, "line": f.line,
         "fingerprint": fp, "message": f.message}
        for f, fp in fingerprinted]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    path.write_text(json.dumps(
        {"version": BASELINE_VERSION,
         "note": "grandfathered pertlint findings; regenerate with "
                 "--write-baseline (see tools/pertlint/README.md)",
         "findings": entries}, indent=1) + "\n")
