"""Baseline file: grandfathered findings that do not gate the build.

The baseline is a checked-in JSON list of finding fingerprints.  A
fingerprint is content-addressed — ``sha1(rule : path : stripped source
line : occurrence-index)`` — so it survives unrelated edits that shift
line numbers, and only breaks when the flagged line itself changes
(at which point the finding deserves a fresh look).

Workflow: ``python -m tools.pertlint <paths> --write-baseline`` snapshots
every current finding; subsequent runs report (and gate on) only
findings that are NOT in the snapshot.  Stale entries — fingerprints no
longer produced by the tree, or pointing at files that no longer exist —
are WARNED about so the baseline shrinks as debt is paid down;
``--update-baseline`` prunes them without grandfathering anything new.

Entries may carry a ``rationale`` field — one line on WHY the finding is
acceptable debt rather than a bug.  Deep (DP-rule) entries are required
to have one (the deep gate warns otherwise); re-snapshotting preserves
rationales by fingerprint so ``--write-baseline`` never erases them.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
from typing import Dict, Iterable, List, Set, Tuple

from tools.pertlint.core import Finding

BASELINE_VERSION = 1


def fingerprint(finding: Finding, line_text: str, occurrence: int) -> str:
    payload = f"{finding.rule}:{finding.path}:{line_text.strip()}:{occurrence}"
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


def fingerprint_findings(findings: Iterable[Finding],
                         sources: Dict[str, List[str]]
                         ) -> List[Tuple[Finding, str]]:
    """Pair each finding with its fingerprint.

    ``sources`` maps path -> source lines.  Identical flagged lines in
    the same file get distinct occurrence indices (in line order) so two
    copies of a violation need two baseline entries.
    """
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule,
                                             f.message)):
        lines = sources.get(f.path, [])
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        key = (f.rule, f.path, text.strip())
        occurrence = seen.get(key, 0)
        seen[key] = occurrence + 1
        out.append((f, fingerprint(f, text, occurrence)))
    return out


def load_entries(path: pathlib.Path) -> List[dict]:
    """Raw entry dicts of a baseline file; missing file = empty baseline."""
    if not path.is_file():
        return []
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: unsupported baseline version "
                         f"{data.get('version')!r}")
    return list(data.get("findings", []))


def load(path: pathlib.Path) -> Set[str]:
    """Fingerprint set of a baseline file; missing file = empty baseline."""
    return {e["fingerprint"] for e in load_entries(path)}


def entry_file_exists(path_str: str,
                      baseline_path: pathlib.Path = None) -> bool:
    """Does an entry's flagged file exist?  Relative entry paths are
    checked against the CWD and — because relative baseline paths are
    repo-root-relative while the process may run from elsewhere — every
    ancestor of the baseline file.  Errs toward "exists": the callers
    prune/warn on the negative, and a wrong-CWD invocation must not
    wipe grandfathered debt.
    """
    p = pathlib.Path(path_str or "")
    if p.is_absolute() or baseline_path is None:
        return p.is_file()
    if p.is_file():
        return True
    return any((root / p).is_file()
               for root in pathlib.Path(baseline_path).resolve().parents)


def missing_file_entries(entries: List[dict],
                         baseline_path: pathlib.Path = None) -> List[dict]:
    """Entries whose flagged file no longer exists on disk — dead weight
    a lint run can never match (the lint walks real files only)."""
    return [e for e in entries
            if not entry_file_exists(e.get("path", ""), baseline_path)]


def rationales(entries: List[dict]) -> Dict[str, str]:
    """fingerprint -> rationale for every entry that carries one."""
    return {e["fingerprint"]: e["rationale"]
            for e in entries if e.get("rationale")}


def write(path: pathlib.Path,
          fingerprinted: List[Tuple[Finding, str]],
          retained_entries: List[dict] = (),
          keep_rationales: Dict[str, str] = None) -> None:
    """Write retained (out-of-scope) entries + the fresh snapshot.

    ``retained_entries`` are prior entries for paths/rules NOT covered by
    the snapshot run — a partial ``--write-baseline`` must not silently
    drop the rest of the grandfathered debt.  ``keep_rationales``
    (fingerprint -> text) re-attaches rationales to re-snapshotted
    entries so regenerating the file never erases the documented WHY.
    """
    keep_rationales = keep_rationales or {}
    entries = list(retained_entries)
    for f, fp in fingerprinted:
        entry = {"rule": f.rule, "path": f.path, "line": f.line,
                 "fingerprint": fp, "message": f.message}
        if fp in keep_rationales:
            entry["rationale"] = keep_rationales[fp]
        entries.append(entry)
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    path.write_text(json.dumps(
        {"version": BASELINE_VERSION,
         "note": "grandfathered pertlint findings; regenerate with "
                 "--write-baseline (see tools/pertlint/README.md)",
         "findings": entries}, indent=1) + "\n")
