"""Drive the flow pass and feed it through pertlint's shared machinery.

``flow_lint`` parses the whole package once (stdlib ast — nothing is
imported or traced), builds the call graph + taint summaries, computes
the per-entry-point program-identity report, runs the FL rules, then
applies the SAME inline-suppression and content-addressed-baseline
filtering as the AST and deep layers — ``python -m tools.pertlint
--flow`` is the third gate with the same one workflow.

Flow findings anchor at real source lines (the collective call, the
jit call site, the jit decoration), so ``# pertlint: disable=FL001``
suppresses in place and baseline entries are content-addressed to the
line's text.  Like the deep layer, baselined flow entries are expected
to carry a one-line ``rationale``.

The identity report (``FlowStats.identity_report``) is the payload of
``artifacts/PROGRAM_IDENTITY.json`` — the machine-readable certificate
the persisted AOT executable cache keys against: per registered deep
entry point, its identity inputs, their config-field provenance, and a
hash-coverage verdict (``covered`` / ``leak`` / ``incomplete``).
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.pertlint import baseline as baseline_mod
from tools.pertlint import suppress
from tools.pertlint.core import Finding, Rule, all_rules
from tools.pertlint.engine import LintResult
from tools.pertlint.flow import callgraph as cg
from tools.pertlint.flow import identity as ident
from tools.pertlint.flow.rules_flow import FlowContext

REPO_ROOT = pathlib.Path(__file__).resolve().parents[3]
DEFAULT_PACKAGE_ROOT = REPO_ROOT / "scdna_replication_tools_tpu"

# deep-registry entry name -> package-relative jit function, for the
# entries whose identity IS a jit decoration.  test_pertlint_flow pins
# that this map + _SYNTHETIC_ENTRIES covers the registry exactly, so a
# new deep entry point without an identity mapping fails loudly.
ENTRY_JIT = {
    "fit": "infer.svi._run_fit",
    "fit_chunk": "infer.svi._run_fit_chunk",
    "fit_chunk_binary": "infer.svi._run_fit_chunk",
    "decode_slab": "models.pert._decode_slab",
    "decode_slab_binary": "models.pert._decode_slab",
    "ppc": "models.pert._ppc_slab",
}

# entries whose program identity is structural, not a jit decoration:
# anchor-function suffix, provenance atoms, note
_SYNTHETIC_ENTRIES = {
    "loss": ("._PertLossFn.__call__", ("model-spec",),
             "identity is the frozen PertModelSpec (hashable by value) "
             "— itself built from hash-included fields (P, K, J, "
             "upsilon, ...) plus data dims"),
    "sharded_batch": (".shard_batch",
                      ("layout-contract", "bucket:cells", "bucket:loci"),
                      "identity is the mesh extents + the layout "
                      "factory's PartitionSpecs — the DP006/DP007 "
                      "machine-checked contract"),
    "sharded_params": (".shard_params",
                       ("layout-contract", "bucket:cells", "bucket:loci"),
                       "identity is the mesh extents + the layout "
                       "factory's PartitionSpecs — the DP006/DP007 "
                       "machine-checked contract"),
}

# per-entry provenance of the dynamic arg shapes/dtypes: the pad/chunk
# knobs are hash-included config fields; the rest is the data itself
_SHAPE_PROVENANCE = {
    "fit": ("config:pad_cells_to", "config:pad_loci_to",
            "config:cell_chunk", "data-shape"),
    "fit_chunk": ("config:pad_cells_to", "config:pad_loci_to",
                  "config:cell_chunk", "data-shape"),
    "fit_chunk_binary": ("config:pad_cells_to", "config:pad_loci_to",
                         "config:cell_chunk", "data-shape"),
    "decode_slab": ("config:cell_chunk", "data-shape"),
    "decode_slab_binary": ("config:cell_chunk", "data-shape"),
    "ppc": ("config:cell_chunk", "data-shape"),
}


@dataclasses.dataclass
class FlowStats:
    """Run facts the CLI reports next to the LintResult."""
    modules: int
    functions: int
    collective_bearing: int
    entries: List[str]                  # identity-certified entry names
    verdicts: Dict[str, str]            # entry -> covered|leak|incomplete
    identity_report: dict
    unrationalized: List[str] = dataclasses.field(default_factory=list)


def _flow_rules(select: Optional[Set[str]] = None) -> List[Rule]:
    rules = all_rules(kind="flow")
    if select is not None:
        rules = [r for r in rules if r.id in select]
    return rules


def non_hash_fields_of(graph: cg.PackageGraph) -> Tuple[str, ...]:
    """The declared hash-exclusion contract, read from the package's
    ``config.NON_HASH_FIELDS`` constant — statically, so fixtures can
    declare their own."""
    mod = graph.modules.get(f"{graph.package}.config")
    if mod is None:
        return ()
    const = mod.constants.get("NON_HASH_FIELDS")
    if const is None:
        return ()
    return ident._tuple_of_strings(const) or ()


def _registry_names() -> List[str]:
    """The deep registry's entry names (entrypoints.py is importable
    without jax — the ``--list-rules`` contract the deep layer keeps)."""
    from tools.pertlint.deep import entrypoints
    return list(entrypoints.REGISTRY)


def _find_suffix(graph: cg.PackageGraph, suffix: str
                 ) -> Optional[cg.FunctionInfo]:
    for qual, fn in graph.functions.items():
        if qual.endswith(suffix):
            return fn
    return None


def build_identity_report(graph: cg.PackageGraph,
                          resolver: ident.ProvenanceResolver,
                          jit_entries: Dict[str, ident.JitEntry],
                          non_hash_fields: Tuple[str, ...],
                          registry_names: Optional[Sequence[str]] = None
                          ) -> dict:
    """The PROGRAM_IDENTITY.json payload.

    With ``registry_names`` (the real package): one row per registered
    deep entry point, via ENTRY_JIT/_SYNTHETIC_ENTRIES.  Without (test
    fixtures): one row per discovered jit function, keyed by its name.
    """
    entries: List[dict] = []
    if registry_names is None:
        for qual, entry in sorted(jit_entries.items()):
            entries.append(ident.build_entry_report(
                qual.rsplit(".", 1)[-1], entry, resolver, non_hash_fields))
    else:
        for name in registry_names:
            rel = ENTRY_JIT.get(name)
            if rel is not None:
                qual = f"{graph.package}.{rel}"
                entry = jit_entries.get(qual)
                if entry is None:
                    entries.append(_unmapped(graph, name,
                                             f"jit function {qual} not "
                                             f"found/not jit-decorated"))
                    continue
                notes = []
                if name.endswith("_binary"):
                    notes.append("binary-encoded variant: same jit "
                                 "function, Kb-plane shapes")
                entries.append(ident.build_entry_report(
                    name, entry, resolver, non_hash_fields,
                    shape_provenance=_SHAPE_PROVENANCE.get(name, ()),
                    notes=notes))
            elif name in _SYNTHETIC_ENTRIES:
                suffix, prov, note = _SYNTHETIC_ENTRIES[name]
                anchor = _find_suffix(graph, suffix)
                if anchor is None:
                    entries.append(_unmapped(graph, name,
                                             f"anchor '{suffix}' not "
                                             f"found in package"))
                    continue
                entries.append(ident.synthetic_entry_report(
                    name, prov, non_hash_fields,
                    graph.rel_path(anchor.path), anchor.line,
                    notes=[note]))
            else:
                entries.append(_unmapped(graph, name,
                                         "deep registry entry has no "
                                         "identity mapping (extend "
                                         "flow/engine.py ENTRY_JIT)"))
    return {
        "schema": ident.SCHEMA,
        "package": graph.package,
        "non_hash_fields": sorted(non_hash_fields),
        "jit_cache_key_includes_jax_version": True,
        "entries": entries,
    }


def _unmapped(graph: cg.PackageGraph, name: str, why: str) -> dict:
    # an unmapped registry entry must gate (FL004), not vanish
    init = graph.modules.get(f"{graph.package}")
    path = graph.rel_path(init.path) if init else graph.package
    return ident.synthetic_entry_report(
        name, (f"unknown:{why}",), (), path, 1, notes=[why])


def build_flow_context(package_root: Optional[pathlib.Path] = None,
                       package: Optional[str] = None,
                       registry_names: Optional[Sequence[str]] = "auto"
                       ) -> FlowContext:
    """Parse + summarise one package into the context the FL rules see.

    ``registry_names='auto'`` (the real gate) reads the deep registry;
    pass an explicit list, or None for fixture packages (every
    discovered jit function becomes an identity entry).
    """
    root = pathlib.Path(package_root) if package_root is not None \
        else DEFAULT_PACKAGE_ROOT
    graph = cg.build_graph(root, package)
    names = _registry_names() if registry_names == "auto" \
        else registry_names
    non_hash = non_hash_fields_of(graph)
    jit_entries = ident.find_jit_functions(graph)
    resolver = ident.ProvenanceResolver(graph)
    report = build_identity_report(graph, resolver, jit_entries,
                                   non_hash, names)
    return FlowContext(graph=graph, non_hash_fields=non_hash,
                       jit_entries=jit_entries, resolver=resolver,
                       identity_report=report)


def run_flow_rules(select: Optional[Set[str]] = None,
                   package_root: Optional[pathlib.Path] = None,
                   ctx: Optional[FlowContext] = None
                   ) -> Tuple[List[Finding], FlowStats]:
    """Build the graph and run the FL rules -> raw (unfiltered)
    findings + stats.  Parse failures of package modules propagate as
    findings-free stats with the errors recorded on the graph — the
    gate surfaces them via the CLI's parse-error channel."""
    rules = _flow_rules(select)
    if not rules:
        empty = {"schema": ident.SCHEMA, "package": "", "entries": [],
                 "non_hash_fields": [],
                 "jit_cache_key_includes_jax_version": True}
        return [], FlowStats(modules=0, functions=0, collective_bearing=0,
                             entries=[], verdicts={},
                             identity_report=empty)
    if ctx is None:
        ctx = build_flow_context(package_root)
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    report = ctx.identity_report
    stats = FlowStats(
        modules=len(ctx.graph.modules),
        functions=len(ctx.graph.functions),
        collective_bearing=len(ctx.graph.collective_bearing),
        entries=[e["name"] for e in report["entries"]],
        verdicts={e["name"]: e["verdict"] for e in report["entries"]},
        identity_report=report)
    return findings, stats


def _load_sources(findings: List[Finding]) -> Dict[str, List[str]]:
    sources: Dict[str, List[str]] = {}
    for f in findings:
        if f.path in sources:
            continue
        p = pathlib.Path(f.path)
        sources[f.path] = p.read_text().splitlines() if p.is_file() else []
    return sources


def _filter_suppressed(findings: List[Finding],
                       sources: Dict[str, List[str]]
                       ) -> Tuple[List[Finding], List[Finding]]:
    kept: List[Finding] = []
    dropped: List[Finding] = []
    parsed: Dict[str, tuple] = {}
    for f in findings:
        if f.path not in parsed:
            text = "\n".join(sources.get(f.path, []))
            parsed[f.path] = suppress.parse_suppressions(text)
        per_line, file_wide = parsed[f.path]
        if suppress.is_suppressed(f.rule, f.line, per_line, file_wide):
            dropped.append(f)
        else:
            kept.append(f)
    return kept, dropped


def flow_lint(select: Optional[Set[str]] = None,
              baseline_path: Optional[pathlib.Path] = None,
              package_root: Optional[pathlib.Path] = None
              ) -> Tuple[LintResult, FlowStats,
                         List[Tuple[Finding, str]]]:
    """The flow gate -> (result, stats, fingerprinted findings).

    Mirrors ``deep_lint``: the fingerprinted list covers ALL flow
    findings so the CLI can fold them into ``--write-baseline`` /
    ``--update-baseline`` against the one shared baseline file.
    """
    raw, stats = run_flow_rules(select, package_root)
    sources = _load_sources(raw)
    kept, suppressed = _filter_suppressed(raw, sources)
    fingerprinted = baseline_mod.fingerprint_findings(kept, sources)

    entries = baseline_mod.load_entries(baseline_path) if baseline_path \
        else []
    known = {e["fingerprint"] for e in entries}
    new = [f for f, fp in fingerprinted if fp not in known]
    baselined = [f for f, fp in fingerprinted if fp in known]

    produced = {fp for _, fp in fingerprinted}
    rule_ids = {r.id for r in _flow_rules(select)}
    stale = {e["fingerprint"] for e in entries
             if e["rule"] in rule_ids and e["fingerprint"] not in produced}
    rationale = baseline_mod.rationales(entries)
    matched = {fp for _, fp in fingerprinted if fp in known}
    stats.unrationalized = sorted(
        e["fingerprint"] for e in entries
        if e["fingerprint"] in matched and e["fingerprint"] not in rationale)

    result = LintResult(new=new, baselined=baselined,
                        suppressed=suppressed, stale_baseline=stale,
                        parse_errors=[], files_checked=len(sources))
    return result, stats, fingerprinted
