"""Drive the flow pass and feed it through pertlint's shared machinery.

``flow_lint`` parses the whole package once (stdlib ast — nothing is
imported or traced), builds the call graph + taint summaries, computes
the per-entry-point program-identity report, runs the FL rules, then
applies the SAME inline-suppression and content-addressed-baseline
filtering as the AST and deep layers — ``python -m tools.pertlint
--flow`` is the third gate with the same one workflow.

Flow findings anchor at real source lines (the collective call, the
jit call site, the jit decoration), so ``# pertlint: disable=FL001``
suppresses in place and baseline entries are content-addressed to the
line's text.  Like the deep layer, baselined flow entries are expected
to carry a one-line ``rationale``.

The identity report (``FlowStats.identity_report``) is the payload of
``artifacts/PROGRAM_IDENTITY.json`` — the machine-readable certificate
the persisted AOT executable cache keys against: per registered deep
entry point, its identity inputs, their config-field provenance, and a
hash-coverage verdict (``covered`` / ``leak`` / ``incomplete``).
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.pertlint import baseline as baseline_mod
from tools.pertlint import suppress
from tools.pertlint.core import Finding, Rule, all_rules
from tools.pertlint.engine import LintResult
from tools.pertlint.flow import callgraph as cg
from tools.pertlint.flow import identity as ident
from tools.pertlint.flow.rules_flow import FlowContext

REPO_ROOT = pathlib.Path(__file__).resolve().parents[3]
DEFAULT_PACKAGE_ROOT = REPO_ROOT / "scdna_replication_tools_tpu"

# deep-registry entry name -> package-relative jit function, for the
# entries whose identity IS a jit decoration.  test_pertlint_flow pins
# that this map + _SYNTHETIC_ENTRIES covers the registry exactly, so a
# new deep entry point without an identity mapping fails loudly.
ENTRY_JIT = {
    "fit": "infer.svi._run_fit",
    "fit_chunk": "infer.svi._run_fit_chunk",
    "fit_chunk_binary": "infer.svi._run_fit_chunk",
    "decode_slab": "models.pert._decode_slab",
    "decode_slab_binary": "models.pert._decode_slab",
    "ppc": "models.pert._ppc_slab",
}

# entries whose program identity is structural, not a jit decoration:
# anchor-function suffix, provenance atoms, note
_SYNTHETIC_ENTRIES = {
    "loss": ("._PertLossFn.__call__", ("model-spec",),
             "identity is the frozen PertModelSpec (hashable by value) "
             "— itself built from hash-included fields (P, K, J, "
             "upsilon, ...) plus data dims"),
    "sharded_batch": (".shard_batch",
                      ("layout-contract", "bucket:cells", "bucket:loci"),
                      "identity is the mesh extents + the layout "
                      "factory's PartitionSpecs — the DP006/DP007 "
                      "machine-checked contract"),
    "sharded_params": (".shard_params",
                       ("layout-contract", "bucket:cells", "bucket:loci"),
                       "identity is the mesh extents + the layout "
                       "factory's PartitionSpecs — the DP006/DP007 "
                       "machine-checked contract"),
}

# provenance of each declared component of the persistent executable
# store's digest (infer/aotcache.py KEY_COMPONENTS — read statically,
# like NON_HASH_FIELDS).  The certificate is two-way: a declared
# component without provenance here, or a certified component missing
# from the declaration, degrades to an ``unknown:`` atom and gates as
# FL004 — the disk-cache key can neither grow nor shrink silently.
_AOT_KEY_PROVENANCE = {
    "program-tag": ("program-tag",),
    "loss-structure": ("model-spec",),
    "optimizer-statics": ("config:learning_rate", "config:max_iter",
                          "config:min_iter", "config:rel_tol",
                          "config:fused_adam",
                          "config:optimizer_state_dtype", "literal"),
    "abstract-signature": ("config:pad_cells_to", "config:pad_loci_to",
                           "config:cell_chunk", "bucket:cells",
                           "bucket:loci", "data-shape"),
    "config-digest": ("config-digest",),
    "jax-version": ("jax-version",),
    "jaxlib-version": ("jaxlib-version",),
    "backend": ("env:backend",),
    "device-kind": ("env:device-kind",),
    "mesh-topology": ("env:mesh-topology",),
}

_AOT_KEY_NOTES = [
    "digest of the persistent executable store (infer/aotcache.py): "
    "canonical key text (tag, loss value, optimiser statics, abstract "
    "signature) + environment facts + the PROGRAM-shaping config "
    "digest (_config_digest over NON_HASH_FIELDS' complement, minus "
    "config.AOT_EXECUTION_ONLY_FIELDS)",
    "AOT_EXECUTION_ONLY_FIELDS (checkpoint_dir, profile_dir, "
    "compile_cache_dir) are stripped from the digest's config hash: "
    "they name where host-side artifacts land, never what XLA "
    "compiles — the serve worker moves checkpoint_dir per request, "
    "and a restarted worker must still disk-hit its predecessor's "
    "executables",
    "a slab<W> tag's width is an abstract-signature fact (the packed "
    "leading dim of every lane-stacked argument), NOT a read of the "
    "hash-excluded config:slab_width placement field",
    "executable_cache_dir itself is hash-excluded by design: it names "
    "WHERE executables persist, and the digest embedding the config "
    "hash would self-invalidate a relocated store",
]

# per-entry provenance of the dynamic arg shapes/dtypes: the pad/chunk
# knobs are hash-included config fields; the rest is the data itself
_SHAPE_PROVENANCE = {
    "fit": ("config:pad_cells_to", "config:pad_loci_to",
            "config:cell_chunk", "data-shape"),
    "fit_chunk": ("config:pad_cells_to", "config:pad_loci_to",
                  "config:cell_chunk", "data-shape"),
    "fit_chunk_binary": ("config:pad_cells_to", "config:pad_loci_to",
                         "config:cell_chunk", "data-shape"),
    "decode_slab": ("config:cell_chunk", "data-shape"),
    "decode_slab_binary": ("config:cell_chunk", "data-shape"),
    "ppc": ("config:cell_chunk", "data-shape"),
}


@dataclasses.dataclass
class FlowStats:
    """Run facts the CLI reports next to the LintResult."""
    modules: int
    functions: int
    collective_bearing: int
    entries: List[str]                  # identity-certified entry names
    verdicts: Dict[str, str]            # entry -> covered|leak|incomplete
    identity_report: dict
    unrationalized: List[str] = dataclasses.field(default_factory=list)


def _flow_rules(select: Optional[Set[str]] = None) -> List[Rule]:
    rules = all_rules(kind="flow")
    if select is not None:
        rules = [r for r in rules if r.id in select]
    return rules


def non_hash_fields_of(graph: cg.PackageGraph) -> Tuple[str, ...]:
    """The declared hash-exclusion contract, read from the package's
    ``config.NON_HASH_FIELDS`` constant — statically, so fixtures can
    declare their own."""
    mod = graph.modules.get(f"{graph.package}.config")
    if mod is None:
        return ()
    const = mod.constants.get("NON_HASH_FIELDS")
    if const is None:
        return ()
    return ident._tuple_of_strings(const) or ()


def build_aot_key_report(graph: cg.PackageGraph,
                         non_hash_fields: Tuple[str, ...]
                         ) -> Optional[dict]:
    """The ``aot_disk_key`` certificate row: the on-disk executable
    store's digest components (infer/aotcache.py KEY_COMPONENTS, read
    statically) cross-checked against ``_AOT_KEY_PROVENANCE``.  None
    when the package has no aotcache module (fixture packages)."""
    mod = graph.modules.get(f"{graph.package}.infer.aotcache")
    if mod is None:
        return None
    const = mod.constants.get("KEY_COMPONENTS")
    declared = (ident._tuple_of_strings(const) or ()) \
        if const is not None else ()
    inputs: Dict[str, Set[str]] = {}
    for comp in declared:
        atoms = _AOT_KEY_PROVENANCE.get(comp)
        if atoms is None:
            atoms = (f"unknown:KEY_COMPONENTS declares '{comp}' but "
                     f"flow/engine.py _AOT_KEY_PROVENANCE certifies no "
                     f"provenance for it",)
        inputs[comp] = set(atoms)
    for comp in _AOT_KEY_PROVENANCE:
        if comp not in declared:
            inputs[comp] = {
                f"unknown:certified component '{comp}' is missing from "
                f"infer/aotcache.py KEY_COMPONENTS — the disk digest "
                f"no longer covers it"}
    if const is None:
        inputs["<KEY_COMPONENTS>"] = {
            "unknown:infer/aotcache.py has no statically-readable "
            "KEY_COMPONENTS literal"}
    # the declared execution-only strip list (config.py), read
    # statically like NON_HASH_FIELDS — recorded for provenance; the
    # runner consumes the same constant when computing the digest
    exec_only: Tuple[str, ...] = ()
    cfg_mod = graph.modules.get(f"{graph.package}.config")
    if cfg_mod is not None:
        eo = cfg_mod.constants.get("AOT_EXECUTION_ONLY_FIELDS")
        if eo is not None:
            exec_only = ident._tuple_of_strings(eo) or ()
    return {
        "name": "aot_disk_key",
        "store": f"{graph.package}.infer.aotcache",
        "path": graph.rel_path(mod.path),
        "line": getattr(const, "lineno", 1) if const is not None else 1,
        "components": list(declared),
        "execution_only_fields": list(exec_only),
        "identity_inputs": [
            {"name": k, "provenance": sorted(v),
             "classification": ident._worst(v, non_hash_fields)}
            for k, v in inputs.items()],
        "verdict": ident.entry_verdict(inputs, non_hash_fields),
        "notes": list(_AOT_KEY_NOTES),
    }


def _registry_names() -> List[str]:
    """The deep registry's entry names (entrypoints.py is importable
    without jax — the ``--list-rules`` contract the deep layer keeps)."""
    from tools.pertlint.deep import entrypoints
    return list(entrypoints.REGISTRY)


def _find_suffix(graph: cg.PackageGraph, suffix: str
                 ) -> Optional[cg.FunctionInfo]:
    for qual, fn in graph.functions.items():
        if qual.endswith(suffix):
            return fn
    return None


def build_identity_report(graph: cg.PackageGraph,
                          resolver: ident.ProvenanceResolver,
                          jit_entries: Dict[str, ident.JitEntry],
                          non_hash_fields: Tuple[str, ...],
                          registry_names: Optional[Sequence[str]] = None
                          ) -> dict:
    """The PROGRAM_IDENTITY.json payload.

    With ``registry_names`` (the real package): one row per registered
    deep entry point, via ENTRY_JIT/_SYNTHETIC_ENTRIES.  Without (test
    fixtures): one row per discovered jit function, keyed by its name.
    """
    entries: List[dict] = []
    if registry_names is None:
        for qual, entry in sorted(jit_entries.items()):
            entries.append(ident.build_entry_report(
                qual.rsplit(".", 1)[-1], entry, resolver, non_hash_fields))
    else:
        for name in registry_names:
            rel = ENTRY_JIT.get(name)
            if rel is not None:
                qual = f"{graph.package}.{rel}"
                entry = jit_entries.get(qual)
                if entry is None:
                    entries.append(_unmapped(graph, name,
                                             f"jit function {qual} not "
                                             f"found/not jit-decorated"))
                    continue
                notes = []
                if name.endswith("_binary"):
                    notes.append("binary-encoded variant: same jit "
                                 "function, Kb-plane shapes")
                entries.append(ident.build_entry_report(
                    name, entry, resolver, non_hash_fields,
                    shape_provenance=_SHAPE_PROVENANCE.get(name, ()),
                    notes=notes))
            elif name in _SYNTHETIC_ENTRIES:
                suffix, prov, note = _SYNTHETIC_ENTRIES[name]
                anchor = _find_suffix(graph, suffix)
                if anchor is None:
                    entries.append(_unmapped(graph, name,
                                             f"anchor '{suffix}' not "
                                             f"found in package"))
                    continue
                entries.append(ident.synthetic_entry_report(
                    name, prov, non_hash_fields,
                    graph.rel_path(anchor.path), anchor.line,
                    notes=[note]))
            else:
                entries.append(_unmapped(graph, name,
                                         "deep registry entry has no "
                                         "identity mapping (extend "
                                         "flow/engine.py ENTRY_JIT)"))
    report = {
        "schema": ident.SCHEMA,
        "package": graph.package,
        "non_hash_fields": sorted(non_hash_fields),
        "jit_cache_key_includes_jax_version": True,
        "entries": entries,
    }
    # the persistent executable store's digest contract rides the same
    # certificate (schema v2): absent for packages without an aotcache
    # module, so fixture runs and their pins are untouched
    aot = build_aot_key_report(graph, non_hash_fields)
    if aot is not None:
        report["aot_disk_key"] = aot
    return report


def _unmapped(graph: cg.PackageGraph, name: str, why: str) -> dict:
    # an unmapped registry entry must gate (FL004), not vanish
    init = graph.modules.get(f"{graph.package}")
    path = graph.rel_path(init.path) if init else graph.package
    return ident.synthetic_entry_report(
        name, (f"unknown:{why}",), (), path, 1, notes=[why])


def build_flow_context(package_root: Optional[pathlib.Path] = None,
                       package: Optional[str] = None,
                       registry_names: Optional[Sequence[str]] = "auto"
                       ) -> FlowContext:
    """Parse + summarise one package into the context the FL rules see.

    ``registry_names='auto'`` (the real gate) reads the deep registry;
    pass an explicit list, or None for fixture packages (every
    discovered jit function becomes an identity entry).
    """
    root = pathlib.Path(package_root) if package_root is not None \
        else DEFAULT_PACKAGE_ROOT
    graph = cg.build_graph(root, package)
    names = _registry_names() if registry_names == "auto" \
        else registry_names
    non_hash = non_hash_fields_of(graph)
    jit_entries = ident.find_jit_functions(graph)
    resolver = ident.ProvenanceResolver(graph)
    report = build_identity_report(graph, resolver, jit_entries,
                                   non_hash, names)
    return FlowContext(graph=graph, non_hash_fields=non_hash,
                       jit_entries=jit_entries, resolver=resolver,
                       identity_report=report)


def run_flow_rules(select: Optional[Set[str]] = None,
                   package_root: Optional[pathlib.Path] = None,
                   ctx: Optional[FlowContext] = None
                   ) -> Tuple[List[Finding], FlowStats]:
    """Build the graph and run the FL rules -> raw (unfiltered)
    findings + stats.  Parse failures of package modules propagate as
    findings-free stats with the errors recorded on the graph — the
    gate surfaces them via the CLI's parse-error channel."""
    rules = _flow_rules(select)
    if not rules:
        empty = {"schema": ident.SCHEMA, "package": "", "entries": [],
                 "non_hash_fields": [],
                 "jit_cache_key_includes_jax_version": True}
        return [], FlowStats(modules=0, functions=0, collective_bearing=0,
                             entries=[], verdicts={},
                             identity_report=empty)
    if ctx is None:
        ctx = build_flow_context(package_root)
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    report = ctx.identity_report
    rows = list(report["entries"])
    if report.get("aot_disk_key"):
        rows.append(report["aot_disk_key"])
    stats = FlowStats(
        modules=len(ctx.graph.modules),
        functions=len(ctx.graph.functions),
        collective_bearing=len(ctx.graph.collective_bearing),
        entries=[e["name"] for e in rows],
        verdicts={e["name"]: e["verdict"] for e in rows},
        identity_report=report)
    return findings, stats


def _load_sources(findings: List[Finding]) -> Dict[str, List[str]]:
    sources: Dict[str, List[str]] = {}
    for f in findings:
        if f.path in sources:
            continue
        p = pathlib.Path(f.path)
        sources[f.path] = p.read_text().splitlines() if p.is_file() else []
    return sources


def _filter_suppressed(findings: List[Finding],
                       sources: Dict[str, List[str]]
                       ) -> Tuple[List[Finding], List[Finding]]:
    kept: List[Finding] = []
    dropped: List[Finding] = []
    parsed: Dict[str, tuple] = {}
    for f in findings:
        if f.path not in parsed:
            text = "\n".join(sources.get(f.path, []))
            parsed[f.path] = suppress.parse_suppressions(text)
        per_line, file_wide = parsed[f.path]
        if suppress.is_suppressed(f.rule, f.line, per_line, file_wide):
            dropped.append(f)
        else:
            kept.append(f)
    return kept, dropped


def flow_lint(select: Optional[Set[str]] = None,
              baseline_path: Optional[pathlib.Path] = None,
              package_root: Optional[pathlib.Path] = None
              ) -> Tuple[LintResult, FlowStats,
                         List[Tuple[Finding, str]]]:
    """The flow gate -> (result, stats, fingerprinted findings).

    Mirrors ``deep_lint``: the fingerprinted list covers ALL flow
    findings so the CLI can fold them into ``--write-baseline`` /
    ``--update-baseline`` against the one shared baseline file.
    """
    raw, stats = run_flow_rules(select, package_root)
    sources = _load_sources(raw)
    kept, suppressed = _filter_suppressed(raw, sources)
    fingerprinted = baseline_mod.fingerprint_findings(kept, sources)

    entries = baseline_mod.load_entries(baseline_path) if baseline_path \
        else []
    known = {e["fingerprint"] for e in entries}
    new = [f for f, fp in fingerprinted if fp not in known]
    baselined = [f for f, fp in fingerprinted if fp in known]

    produced = {fp for _, fp in fingerprinted}
    rule_ids = {r.id for r in _flow_rules(select)}
    stale = {e["fingerprint"] for e in entries
             if e["rule"] in rule_ids and e["fingerprint"] not in produced}
    rationale = baseline_mod.rationales(entries)
    matched = {fp for _, fp in fingerprinted if fp in known}
    stats.unrationalized = sorted(
        e["fingerprint"] for e in entries
        if e["fingerprint"] in matched and e["fingerprint"] not in rationale)

    result = LintResult(new=new, baselined=baselined,
                        suppressed=suppressed, stale_baseline=stale,
                        parse_errors=[], files_checked=len(sources))
    return result, stats, fingerprinted
