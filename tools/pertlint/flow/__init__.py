"""pertlint-flow: the interprocedural SPMD/program-identity layer.

Third analysis layer beside the AST rules (PLnnn, per-file) and the
deep jaxpr/sharding layer (DPnnn, traced programs).  The flow layer
(FLnnn) parses the WHOLE package once, builds a call graph with
per-function summaries (rank/count taint, guard stacks, collective
closure) and dataflow from ``PertConfig`` fields to the jit
boundaries, then checks two properties nothing per-file or per-program
can see:

* **SPMD discipline** — no collective (``barrier``,
  ``sync_global_devices``, allgather, the two-phase checkpoint commit)
  is reachable only under rank-divergent control flow (FL001/FL002),
  and the host-global-fetch sites that block mesh-native multi-host
  decode are inventoried (FL006);
* **program identity** — the config hash provably covers everything
  that reaches compiled-program identity (static argnames, shapes,
  dtypes) while the hash-excluded fields provably never do
  (FL003/FL004/FL005), certified per entry point in
  ``artifacts/PROGRAM_IDENTITY.json``.

Pure stdlib (ast + tokenize): ``python -m tools.pertlint --flow``
needs no jax and traces nothing — it reads source.
"""
