"""The FL rules: SPMD discipline + program identity over the call graph.

Each rule's ``check`` receives a :class:`FlowContext` — the parsed
package graph, the hash-exclusion contract, the jit entries and the
program-identity report — and yields findings anchored at real source
lines, so pertlint's inline suppression and content-addressed baseline
apply to the flow layer unchanged.

SPMD family (the PR-11 deadlock classes, machine-checked):

* FL001 — a collective is reachable only under rank-divergent control
  flow: an ``if jax.process_index() == 0:`` branch, the shadow of a
  rank-guarded early return, or a per-rank ``except`` arm.  Every
  process must enter every collective or the others hang forever.
* FL002 — two branches of one conditional issue collectives in
  different sequences; unless the condition is provably count-uniform,
  ranks can disagree on the branch and the collectives cross-match.
* FL006 (warning) — host-side ``np.asarray``-style fetch of array
  values on a path that runs under >1 processes: each host sees only
  its addressable shards, so the fetch silently computes on a fraction
  of the data.  The inventory is the work list for mesh-native
  decode/QC; it reports but never gates.

Program-identity family (the AOT-cache-key soundness certificate):

* FL003 — a hash-EXCLUDED config field (``config.NON_HASH_FIELDS``)
  reaches program identity: a static argname, a pad/shape/bucket
  computation, or a dtype choice.  Two configs that hash equal would
  compile different programs — the cache would serve the wrong one.
* FL004 — an identity input of a jit entry point is NOT derivable from
  hash-included config fields + bucket dims + data shapes + the jax
  version: the config hash under-determines the program, so equal
  hashes do not imply equal executables.
* FL005 — retrace hazard at a jit call site: an unhashable container
  literal fed to a static argname (every call raises or retraces), or
  a bare weak-typed Python scalar fed to a dynamic argument (its weak
  dtype makes a second trace for an otherwise-identical call).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.pertlint.core import Finding, Rule, register
from tools.pertlint.flow import callgraph as cg
from tools.pertlint.flow import identity as ident

# kwarg names whose value becomes an array shape/padding/dtype — the
# non-static-argname ways a value can reach program identity
SHAPE_SINK_KWARGS = {"pad_cells_to", "pad_loci_to", "pad_to", "shape",
                     "dtype", "moment_dtype", "optimizer_state_dtype"}
SHAPE_SINK_CALLEES = {"astype", "reshape", "pad_cells", "pad_loci",
                      "select_bucket"}


@dataclasses.dataclass
class FlowContext:
    """Everything the FL rules see; built once per run by the engine."""
    graph: cg.PackageGraph
    non_hash_fields: Tuple[str, ...]
    jit_entries: Dict[str, ident.JitEntry]
    resolver: ident.ProvenanceResolver
    identity_report: dict        # the PROGRAM_IDENTITY.json payload


class FlowRule(Rule):
    kind = "flow"
    context = "flow"

    def _finding(self, ctx: FlowContext, path: str, node,
                 message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity,
                       path=ctx.graph.rel_path(path),
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), message=message)


def _divergent_guard(guards: Tuple[cg.Guard, ...]
                     ) -> Optional[cg.Guard]:
    """The first guard frame that makes reachability rank-divergent."""
    for g in guards:
        if g.taint == cg.RANK and g.kind in ("if", "else", "after-return"):
            return g
        if g.kind == "except":
            return g
    return None


@register
class RankGuardedCollective(FlowRule):
    id = "FL001"
    name = "rank-guarded-collective"
    severity = "error"
    description = ("collective (barrier/sync_global_devices/allgather or "
                   "a function that reaches one) under rank-divergent "
                   "control flow — the unguarded ranks hang forever")

    def check(self, ctx: FlowContext) -> Iterable[Finding]:
        for fn in ctx.graph.functions.values():
            for site in ctx.graph.collective_sites(fn):
                g = _divergent_guard(site.guards)
                if g is None:
                    continue
                what = site.resolved or site.raw
                if g.kind == "except":
                    how = (f"inside the per-rank 'except {g.test_text}' "
                           f"arm at line {g.line} — exceptions are "
                           f"rank-local, so only the failing rank enters")
                elif g.kind == "after-return":
                    how = (f"after the rank-guarded early return at line "
                           f"{g.line} ('{g.test_text}') — the returning "
                           f"rank never arrives")
                else:
                    how = (f"under the rank-dependent '{g.test_text}' "
                           f"branch at line {g.line}")
                yield self._finding(
                    ctx, fn.path, site.node,
                    f"collective '{what}' in {fn.qualname} is reachable "
                    f"only {how}; every process must enter every "
                    f"collective (guard on jax.process_count(), which is "
                    f"SPMD-uniform, or restructure so all ranks call it)")


def _collective_sequence(ctx: FlowContext, fn: cg.FunctionInfo,
                         stmts: List[ast.stmt]) -> List[str]:
    """In-order collective tokens issued by a statement list."""
    by_node = {id(s.node): s for s in ctx.graph.collective_sites(fn)}
    out: List[Tuple[int, int, str]] = []
    for s in stmts:
        for sub in ast.walk(s):
            hit = by_node.get(id(sub))
            if hit is not None:
                out.append((sub.lineno, sub.col_offset,
                            hit.resolved or hit.raw))
    out.sort()
    return [t for _, _, t in out]


@register
class CollectiveOrderDivergence(FlowRule):
    id = "FL002"
    name = "collective-order-divergence"
    severity = "error"
    description = ("two branches of one conditional issue collectives in "
                   "different sequences — ranks that disagree on the "
                   "branch cross-match collectives and deadlock")

    def check(self, ctx: FlowContext) -> Iterable[Finding]:
        for fn in ctx.graph.functions.values():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.If) or not node.orelse:
                    continue
                if ctx.graph.expr_taint(node.test, fn) == cg.COUNT:
                    continue    # count-uniform: all ranks take one branch
                a = _collective_sequence(ctx, fn, node.body)
                b = _collective_sequence(ctx, fn, node.orelse)
                if a and b and a != b:
                    yield self._finding(
                        ctx, fn.path, node,
                        f"branches of 'if {_text(node.test)}' in "
                        f"{fn.qualname} issue different collective "
                        f"sequences ({' -> '.join(a)} vs "
                        f"{' -> '.join(b)}); unless every rank takes the "
                        f"same branch these cross-match and deadlock")


def _text(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display only
        return "<expr>"


def _certified_rows(report: dict) -> list:
    """Every certificate row FL003/FL004 must police: the per-entry
    jit rows plus — when present — the persistent executable store's
    ``aot_disk_key`` digest row (same shape by construction)."""
    rows = list(report.get("entries", []))
    aot = report.get("aot_disk_key")
    if aot:
        rows.append(aot)
    return rows


def _excluded_reads(expr: ast.expr, fn: cg.FunctionInfo,
                    tainted: Dict[str, Set[str]],
                    non_hash: Tuple[str, ...]) -> Set[str]:
    """Excluded config fields whose value the expression carries."""
    fields: Set[str] = set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute):
            base = cg.dotted_name(sub.value)
            if base and ident._is_config_base(base) \
                    and sub.attr in non_hash:
                fields.add(sub.attr)
        elif isinstance(sub, ast.Name) and sub.id in tainted:
            fields |= tainted[sub.id]
    return fields


@register
class ExcludedFieldReachesIdentity(FlowRule):
    id = "FL003"
    name = "excluded-field-identity-leak"
    severity = "error"
    description = ("hash-excluded config field (NON_HASH_FIELDS) flows "
                   "into program identity (static argname, shape/pad/"
                   "bucket, or dtype) — equal config hashes would compile "
                   "different programs")

    def check(self, ctx: FlowContext) -> Iterable[Finding]:
        # (a) the per-entry-point certificate: leaks visible in the
        # static-argname provenance of any registered jit entry (or in
        # the aot_disk_key digest components)
        for entry in _certified_rows(ctx.identity_report):
            for inp in entry["identity_inputs"]:
                leaked = [a.split(":", 1)[1] for a in inp["provenance"]
                          if a.startswith("config:")
                          and a.split(":", 1)[1] in ctx.non_hash_fields]
                if leaked:
                    yield Finding(
                        rule=self.id, severity=self.severity,
                        path=entry["path"], line=entry["line"], col=0,
                        message=(f"[{entry['name']}] hash-excluded field"
                                 f"(s) {sorted(set(leaked))} reach "
                                 f"identity input '{inp['name']}' — "
                                 f"remove the field from program "
                                 f"identity or from NON_HASH_FIELDS"))
        # (b) the interprocedural sink scan: pad/shape/dtype sinks and
        # jit static args anywhere in the package
        taint_map = _propagate_excluded(ctx)
        for fn in ctx.graph.functions.values():
            tainted = _local_excluded(ctx, fn, taint_map)
            yield from self._sink_scan(ctx, fn, tainted)

    def _sink_scan(self, ctx: FlowContext, fn: cg.FunctionInfo,
                   tainted: Dict[str, Set[str]]) -> Iterable[Finding]:
        for site in fn.calls:
            entry = ctx.jit_entries.get(site.resolved or "")
            if entry is not None:
                for s in entry.static_argnames:
                    bound = ctx.resolver._bind_param(entry.fn, s, site.node)
                    if bound is None:
                        continue
                    fields = _excluded_reads(bound, fn, tainted,
                                             ctx.non_hash_fields)
                    if fields:
                        yield self._finding(
                            ctx, fn.path, site.node,
                            f"hash-excluded field(s) {sorted(fields)} "
                            f"feed static argname '{s}' of jit entry "
                            f"{entry.fn.qualname} — retrace/cache key "
                            f"now depends on an identity-excluded value")
            last = site.raw.rsplit(".", 1)[-1]
            for kw in site.node.keywords:
                if kw.arg in SHAPE_SINK_KWARGS or \
                        (last in SHAPE_SINK_CALLEES and kw.arg):
                    fields = _excluded_reads(kw.value, fn, tainted,
                                             ctx.non_hash_fields)
                    if fields:
                        yield self._finding(
                            ctx, fn.path, site.node,
                            f"hash-excluded field(s) {sorted(fields)} "
                            f"reach shape/dtype argument "
                            f"'{kw.arg}' of {site.raw} — program "
                            f"identity depends on an excluded value")
            if last in ("astype", "reshape") and site.node.args:
                fields = _excluded_reads(site.node.args[0], fn, tainted,
                                         ctx.non_hash_fields)
                if fields:
                    yield self._finding(
                        ctx, fn.path, site.node,
                        f"hash-excluded field(s) {sorted(fields)} reach "
                        f"'{site.raw}' — shape/dtype identity depends "
                        f"on an excluded value")


def _local_excluded(ctx: FlowContext, fn: cg.FunctionInfo,
                    taint_map: Dict[str, Dict[str, Set[str]]]
                    ) -> Dict[str, Set[str]]:
    """name -> excluded fields it carries, within one function."""
    tainted: Dict[str, Set[str]] = {
        p: set(fields) for p, fields in
        taint_map.get(fn.qualname, {}).items()}
    for _ in range(2):
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                fields = _excluded_reads(node.value, fn, tainted,
                                         ctx.non_hash_fields)
                if not fields:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        tainted.setdefault(tgt.id, set()).update(fields)
    return tainted


def _propagate_excluded(ctx: FlowContext
                        ) -> Dict[str, Dict[str, Set[str]]]:
    """Fixpoint: excluded-field taint carried into callee parameters."""
    taint_map: Dict[str, Dict[str, Set[str]]] = {}
    for _ in range(6):
        changed = False
        for fn in ctx.graph.functions.values():
            tainted = _local_excluded(ctx, fn, taint_map)
            for site in fn.calls:
                callee = ctx.graph.functions.get(site.resolved or "")
                if callee is None:
                    continue
                for kw in site.node.keywords:
                    fields = _excluded_reads(kw.value, fn, tainted,
                                             ctx.non_hash_fields)
                    if fields and kw.arg:
                        cur = taint_map.setdefault(
                            callee.qualname, {}).setdefault(kw.arg, set())
                        if not fields <= cur:
                            cur |= fields
                            changed = True
                params = list(callee.params)
                if params and params[0] in ("self", "cls"):
                    params = params[1:]
                for i, arg in enumerate(site.node.args):
                    if i >= len(params) or isinstance(arg, ast.Starred):
                        continue
                    fields = _excluded_reads(arg, fn, tainted,
                                             ctx.non_hash_fields)
                    if fields:
                        cur = taint_map.setdefault(
                            callee.qualname, {}).setdefault(
                                params[i], set())
                        if not fields <= cur:
                            cur |= fields
                            changed = True
        if not changed:
            break
    return taint_map


@register
class CacheKeyIncomplete(FlowRule):
    id = "FL004"
    name = "cache-key-incomplete"
    severity = "error"
    description = ("identity input of a registered jit entry point is "
                   "not derivable from hash-included config fields + "
                   "bucket dims + jax version — equal config hashes "
                   "would not imply equal executables")

    def check(self, ctx: FlowContext) -> Iterable[Finding]:
        for entry in _certified_rows(ctx.identity_report):
            bad = [(inp["name"],
                    [a for a in inp["provenance"]
                     if a.startswith(("unknown:", "api:"))])
                   for inp in entry["identity_inputs"]
                   if inp["classification"] == "incomplete"]
            if not bad:
                continue
            detail = "; ".join(f"'{n}' <- {', '.join(a)}" for n, a in bad)
            yield Finding(
                rule=self.id, severity=self.severity,
                path=entry["path"], line=entry["line"], col=0,
                message=(f"[{entry['name']}] identity input(s) with "
                         f"unresolvable provenance: {detail} — the "
                         f"config hash under-determines this program's "
                         f"identity (declare the source or route it "
                         f"through a hash-included field)"))


@register
class RetraceHazard(FlowRule):
    id = "FL005"
    name = "retrace-hazard"
    severity = "error"
    description = ("jit call site feeds an unhashable container literal "
                   "to a static argname, or a bare weak-typed Python "
                   "scalar to a dynamic argument — each call retraces "
                   "(or raises) instead of reusing the compiled program")

    def check(self, ctx: FlowContext) -> Iterable[Finding]:
        for fn in ctx.graph.functions.values():
            for site in fn.calls:
                entry = ctx.jit_entries.get(site.resolved or "")
                if entry is None:
                    continue
                yield from self._site(ctx, fn, site, entry)

    def _site(self, ctx: FlowContext, fn: cg.FunctionInfo,
              site: cg.CallSite, entry: ident.JitEntry
              ) -> Iterable[Finding]:
        params = list(entry.fn.params)
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        statics = set(entry.static_argnames)
        bound: List[Tuple[str, ast.expr]] = []
        for i, arg in enumerate(site.node.args):
            if i < len(params) and not isinstance(arg, ast.Starred):
                bound.append((params[i], arg))
        for kw in site.node.keywords:
            if kw.arg:
                bound.append((kw.arg, kw.value))
        for name, value in bound:
            if name in statics:
                if isinstance(value, (ast.List, ast.Dict, ast.Set,
                                      ast.ListComp, ast.DictComp,
                                      ast.SetComp)):
                    yield self._finding(
                        ctx, fn.path, value,
                        f"unhashable {type(value).__name__} literal fed "
                        f"to static argname '{name}' of "
                        f"{entry.fn.qualname} — statics must be "
                        f"hashable by value (use a tuple or a frozen "
                        f"dataclass)")
            else:
                weak = (isinstance(value, ast.Constant)
                        and isinstance(value.value, (int, float))
                        and not isinstance(value.value, bool))
                weak = weak or (
                    isinstance(value, ast.Call)
                    and (cg.dotted_name(value.func) or "") in
                    ("int", "float"))
                if weak:
                    yield self._finding(
                        ctx, fn.path, value,
                        f"weak-typed Python scalar fed to dynamic "
                        f"argument '{name}' of {entry.fn.qualname} — "
                        f"pin the dtype (jnp.asarray(..., dtype=...)) "
                        f"or the weak dtype forces a second trace")


@register
class HostFetchOnMultiprocessPath(FlowRule):
    id = "FL006"
    name = "host-global-fetch"
    severity = "warning"
    description = ("host-side np.asarray/device_get of array values on a "
                   "multi-process-reachable path — each host sees only "
                   "its addressable shards (work list for mesh-native "
                   "decode/QC; reports, never gates)")

    def check(self, ctx: FlowContext) -> Iterable[Finding]:
        for fn in ctx.graph.functions.values():
            if fn.qualname not in ctx.graph.multiprocess_reachable:
                continue
            for site in ctx.graph.host_fetch_sites(fn):
                if any(g.count_world == "single" for g in site.guards):
                    continue    # provably single-process branch
                yield self._finding(
                    ctx, fn.path, site.node,
                    f"host fetch '{site.raw}' in {fn.qualname} runs on "
                    f"a multi-process-reachable path; with >1 processes "
                    f"it materialises only this host's addressable "
                    f"shards (guard with process_count()==1, or move "
                    f"the consumer onto the mesh)")
