"""Program-identity provenance: config fields -> jit boundaries.

A compiled program's identity is its static argnames, its input
shapes/dtypes (here: the pad/bucket dims) and the jax version.  The
persisted AOT executable cache (ROADMAP) wants to key programs by the
config hash — which is only sound if

* everything that reaches program identity is derivable from
  hash-INCLUDED config fields (+ bucket dims + data shapes + the jax
  version), and
* no hash-EXCLUDED field (``config.NON_HASH_FIELDS``) ever reaches it.

This module extracts, per jit entry point, the provenance of every
identity input by walking the call graph backwards from the jit
boundary: static kwargs at the call sites, dict-forwarded static
environments (the ``_resolve_program(_run_fit, ..., static_kwargs)``
idiom), parameter lifting through callers, ``self._attr`` resolution
through ``__init__`` — all static, nothing imported.  The result feeds
FL003/FL004 and serialises as ``artifacts/PROGRAM_IDENTITY.json``.

Provenance atom vocabulary (strings in the report):

* ``config:<field>``  — a PertConfig field read (hash-included unless
  the field is in ``non_hash_fields``, which is a FL003 leak);
  ``config:<method>()`` is a method ON the config object — a pure
  derivation of hash-included fields (``cfg.resolved_iters()``)
* ``literal`` / ``default`` — source constants
* ``model-spec``      — the frozen PertModelSpec / loss structure
  (itself built from hash-included fields + data dims)
* ``bucket:<dim>``    — a serve-bucket dimension
* ``data-shape``      — an input array's shape
* ``jax-version``     — jax's own version (jit keys on it natively)
* ``layout-contract`` — the sharding layout factory (DP006/DP007's
  machine-checked contract)
* ``program-tag`` / ``optimizer-statics`` / ``config-digest`` /
  ``jaxlib-version`` / ``env:<fact>`` — components of the persistent
  executable store's digest (infer/aotcache.py KEY_COMPONENTS; the
  ``aot_disk_key`` certificate row, schema v2): the resolver tag, the
  static optimiser kwargs, the behavioural-config hash restricted to
  NON_HASH_FIELDS' complement, and the load-time-revalidated
  environment facts (backend, device kind, mesh topology)
* ``api:<fn>:<param>``    — a caller-supplied public-API input with no
  in-package binding (incomplete for cache-key purposes)
* ``unknown:<what>``  — the analysis could not resolve it (incomplete)
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.pertlint.flow.callgraph import (
    FunctionInfo,
    PackageGraph,
    dotted_name,
)

SCHEMA = "pert-program-identity/v2"

_WRAPPERS = {"int", "float", "str", "bool", "min", "max", "len", "round",
             "tuple", "abs", "sorted"}
_SPEC_NAMES = {"spec", "loss_fn", "model_spec"}
_BUCKET_ATTRS = {"cells", "loci"}
_MAX_DEPTH = 10


@dataclasses.dataclass
class JitEntry:
    """A jit-decorated package function and its declared identity."""
    fn: FunctionInfo
    static_argnames: Tuple[str, ...]
    donate_argnames: Tuple[str, ...]
    decorator_line: int


def _tuple_of_strings(expr: ast.expr) -> Optional[Tuple[str, ...]]:
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    return None


def _names_operand(expr: ast.expr, graph: PackageGraph, module: str
                   ) -> Tuple[str, ...]:
    """Resolve a static/donate argnames expression: a literal tuple of
    strings, or a Name bound to a module-level constant tuple (the
    declared-contract idiom: ``FIT_STATIC_ARGNAMES``)."""
    lit = _tuple_of_strings(expr)
    if lit is not None:
        return lit
    if isinstance(expr, ast.Name):
        const = graph.modules[module].constants.get(expr.id)
        if const is not None:
            return _tuple_of_strings(const) or ()
    return ()


def find_jit_functions(graph: PackageGraph) -> Dict[str, JitEntry]:
    """qualname -> JitEntry for every jit-decorated package function.

    Recognises ``@jax.jit``, ``@jit``, and
    ``@functools.partial(jax.jit, static_argnames=..., ...)``.
    """
    out: Dict[str, JitEntry] = {}
    for fn in graph.functions.values():
        for dec in getattr(fn.node, "decorator_list", []):
            entry = _jit_from_decorator(dec, graph, fn)
            if entry is not None:
                out[fn.qualname] = entry
                break
    return out


def _jit_from_decorator(dec: ast.expr, graph: PackageGraph,
                        fn: FunctionInfo) -> Optional[JitEntry]:
    raw = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
    statics: Tuple[str, ...] = ()
    donates: Tuple[str, ...] = ()
    is_jit = False
    if raw and raw.endswith("jit") and not isinstance(dec, ast.Call):
        is_jit = True
    elif isinstance(dec, ast.Call):
        if raw and raw.endswith("jit"):
            is_jit = True
            kwargs = dec.keywords
        elif raw and raw.endswith("partial") and dec.args and \
                (dotted_name(dec.args[0]) or "").endswith("jit"):
            is_jit = True
            kwargs = dec.keywords
        else:
            kwargs = []
        for kw in kwargs:
            if kw.arg == "static_argnames":
                statics = _names_operand(kw.value, graph, fn.module)
            elif kw.arg == "donate_argnames":
                donates = _names_operand(kw.value, graph, fn.module)
    if not is_jit:
        return None
    return JitEntry(fn=fn, static_argnames=statics,
                    donate_argnames=donates, decorator_line=dec.lineno)


class ProvenanceResolver:
    """Backward dataflow from an expression to its provenance atoms."""

    def __init__(self, graph: PackageGraph):
        self.graph = graph
        self._callers: Optional[Dict[str, List[Tuple[FunctionInfo,
                                                     ast.Call]]]] = None

    # -- call-site index --------------------------------------------------

    def callers_of(self, qualname: str
                   ) -> List[Tuple[FunctionInfo, ast.Call]]:
        if self._callers is None:
            self._callers = {}
            for fn in self.graph.functions.values():
                for site in fn.calls:
                    if site.resolved:
                        self._callers.setdefault(site.resolved, []).append(
                            (fn, site.node))
        return self._callers.get(qualname, [])

    def reference_sites(self, qualname: str
                        ) -> List[Tuple[FunctionInfo, ast.Call]]:
        """Calls that pass ``qualname``'s function AS AN ARGUMENT (the
        forwarding idiom: ``_resolve_program(_run_fit, ...)``)."""
        out = []
        for fn in self.graph.functions.values():
            for site in fn.calls:
                for arg in list(site.node.args) + \
                        [k.value for k in site.node.keywords]:
                    raw = dotted_name(arg)
                    if raw and self.graph.resolve_call(raw, fn) == qualname:
                        out.append((fn, site.node))
                        break
        return out

    # -- expression atoms -------------------------------------------------

    def atoms(self, expr: ast.expr, fn: Optional[FunctionInfo],
              depth: int = 0,
              seen: Optional[Set[Tuple[str, str]]] = None) -> Set[str]:
        seen = seen if seen is not None else set()
        if depth > _MAX_DEPTH:
            return {"unknown:depth-limit"}
        if isinstance(expr, ast.Constant):
            return {"literal"}
        if isinstance(expr, ast.Name):
            return self._name_atoms(expr.id, fn, depth, seen)
        if isinstance(expr, ast.Attribute):
            return self._attr_atoms(expr, fn, depth, seen)
        if isinstance(expr, ast.Call):
            return self._call_atoms(expr, fn, depth, seen)
        if isinstance(expr, (ast.BinOp, ast.BoolOp, ast.Compare,
                             ast.IfExp, ast.UnaryOp)):
            out: Set[str] = set()
            for c in ast.iter_child_nodes(expr):
                if isinstance(c, ast.expr):
                    out |= self.atoms(c, fn, depth + 1, seen)
            return out or {"literal"}
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for e in expr.elts:
                out |= self.atoms(e, fn, depth + 1, seen)
            return out or {"literal"}
        if isinstance(expr, ast.Dict):
            out = set()
            for v in expr.values:
                out |= self.atoms(v, fn, depth + 1, seen)
            return out or {"literal"}
        if isinstance(expr, ast.Subscript):
            return self.atoms(expr.value, fn, depth + 1, seen)
        if isinstance(expr, (ast.Lambda,)):
            return {"model-spec"}
        try:
            desc = ast.unparse(expr)
        except Exception:  # noqa: BLE001 — display only
            desc = type(expr).__name__
        return {f"unknown:{desc[:40]}"}

    def _name_atoms(self, name: str, fn: Optional[FunctionInfo],
                    depth: int, seen: Set[Tuple[str, str]]) -> Set[str]:
        if name in _SPEC_NAMES:
            return {"model-spec"}
        if name in ("pad_cells_to", "pad_loci_to", "cell_chunk"):
            # the bucket dims by their canonical knob names — they are
            # ALSO hash-included config fields; tag both facets
            return {f"config:{name}"}
        scope = fn
        while scope is not None:
            # params/locals of this function, then of each enclosing
            # function (free variables in a closure read outer scope)
            if name in scope.params:
                return self._param_atoms(scope, name, depth, seen)
            assigns = self._local_assigns(scope, name)
            if assigns:
                out: Set[str] = set()
                for value in assigns:
                    out |= self.atoms(value, scope, depth + 1, seen)
                return out
            scope = self.graph.functions.get(scope.parent) \
                if scope.parent else None
        if fn is not None:
            const = self.graph.modules[fn.module].constants.get(name)
            if const is not None:
                return self.atoms(const, None, depth + 1, seen)
        return {f"unknown:{name}"}

    def _attr_atoms(self, expr: ast.Attribute, fn: Optional[FunctionInfo],
                    depth: int, seen: Set[Tuple[str, str]]) -> Set[str]:
        base = dotted_name(expr.value)
        if base and _is_config_base(base):
            return {f"config:{expr.attr}"}
        if expr.attr == "shape" or (base and base.endswith(".shape")):
            return {"data-shape"}
        if expr.attr == "__version__":
            return {"jax-version"}
        if base == "bucket" and expr.attr in _BUCKET_ATTRS:
            return {f"bucket:{expr.attr}"}
        if base == "self" and fn is not None and fn.cls:
            assigns = self.graph.modules[fn.module].class_attrs.get(
                (fn.cls, expr.attr), [])
            if assigns:
                out: Set[str] = set()
                for value in assigns:
                    # evaluated without local scope: config-reads and
                    # constants still resolve, locals degrade to unknown
                    out |= self.atoms(value, None, depth + 1, seen)
                return out
        try:
            desc = ast.unparse(expr)
        except Exception:  # noqa: BLE001
            desc = expr.attr
        return {f"unknown:{desc[:40]}"}

    def _call_atoms(self, expr: ast.Call, fn: Optional[FunctionInfo],
                    depth: int, seen: Set[Tuple[str, str]]) -> Set[str]:
        raw = dotted_name(expr.func) or ""
        last = raw.rsplit(".", 1)[-1]
        args = list(expr.args) + [k.value for k in expr.keywords]
        base = raw.rsplit(".", 1)[0] if "." in raw else ""
        if base and _is_config_base(base):
            # a method ON the config object (cfg.resolved_iters()):
            # the value is a pure derivation of hash-included fields
            return {f"config:{last}()"}
        if last in _WRAPPERS or last in ("resolve_fused_adam",
                                         "moment_jnp_dtype"):
            out: Set[str] = set()
            for a in args:
                out |= self.atoms(a, fn, depth + 1, seen)
            return out or {"literal"}
        if last and (last[0].isupper() or last.startswith("_Pert")):
            # constructor: the structure is its (resolved) arguments
            out = set()
            for a in args:
                out |= self.atoms(a, fn, depth + 1, seen)
            return out or {"model-spec"}
        if not args:
            return {f"unknown:{raw or 'call'}()"}
        out = set()
        for a in args:
            out |= self.atoms(a, fn, depth + 1, seen)
        return out

    def _local_assigns(self, fn: FunctionInfo, name: str
                       ) -> List[ast.expr]:
        out = []
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        out.append(node.value)
            elif isinstance(node, ast.AnnAssign) and node.value and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id == name:
                out.append(node.value)
        return out

    def _param_atoms(self, fn: FunctionInfo, param: str, depth: int,
                     seen: Set[Tuple[str, str]]) -> Set[str]:
        key = (fn.qualname, param)
        if key in seen:
            return set()
        seen = seen | {key}
        default = self._param_default(fn, param)
        bindings = []
        for caller, call in self.callers_of(fn.qualname):
            bound = self._bind_param(fn, param, call)
            if bound is not None:
                bindings.append((caller, bound))
        out: Set[str] = set()
        for caller, bound in bindings:
            out |= self.atoms(bound, caller, depth + 1, seen)
        if not bindings:
            out |= ({"default"} if default is not None
                    else {f"api:{fn.qualname.rsplit('.', 1)[-1]}:{param}"})
        elif default is not None:
            # some call sites may omit it: the default is reachable too
            out |= {"default"}
        return out

    def _param_default(self, fn: FunctionInfo, param: str
                       ) -> Optional[ast.expr]:
        a = fn.node.args
        pos = a.posonlyargs + a.args
        n_def = len(a.defaults)
        for i, p in enumerate(pos):
            if p.arg == param:
                j = i - (len(pos) - n_def)
                return a.defaults[j] if j >= 0 else None
        for i, p in enumerate(a.kwonlyargs):
            if p.arg == param:
                return a.kw_defaults[i]
        return None

    def _bind_param(self, fn: FunctionInfo, param: str, call: ast.Call
                    ) -> Optional[ast.expr]:
        for kw in call.keywords:
            if kw.arg == param:
                return kw.value
        params = list(fn.params)
        if params and params[0] in ("self", "cls"):
            params = params[1:]     # bound-method call convention
        try:
            idx = params.index(param)
        except ValueError:
            return None
        if idx < len(call.args):
            arg = call.args[idx]
            return None if isinstance(arg, ast.Starred) else arg
        return None

    # -- static-argname provenance ---------------------------------------

    def static_provenance(self, entry: JitEntry
                          ) -> Dict[str, Set[str]]:
        """static argname -> provenance atoms, unioned over every
        direct call site and every dict-forwarding site."""
        fn = entry.fn
        out: Dict[str, Set[str]] = {s: set() for s in entry.static_argnames}
        for caller, call in self.callers_of(fn.qualname):
            for s in entry.static_argnames:
                bound = self._bind_param(fn, s, call)
                if bound is not None:
                    out[s] |= self.atoms(bound, caller, 1)
        for caller, call in self.reference_sites(fn.qualname):
            env = self._dict_env(caller, entry.static_argnames)
            names_in_call = {dotted_name(a) for a in call.args} | \
                {dotted_name(k.value) for k in call.keywords}
            for s in entry.static_argnames:
                if s in env:
                    out[s] |= self.atoms(env[s], caller, 1)
                elif s in names_in_call:
                    out[s] |= self._name_atoms(s, caller, 1, set())
        for s in entry.static_argnames:
            if not out[s]:
                d = self._param_default(fn, s)
                out[s] = {"default"} if d is not None else \
                    {f"api:{fn.qualname.rsplit('.', 1)[-1]}:{s}"}
        return out

    def _dict_env(self, fn: FunctionInfo, keys: Sequence[str]
                  ) -> Dict[str, ast.expr]:
        """Locals assigned ``dict(k=v, ...)`` / ``{...}`` whose keys
        overlap the static argnames — the forwarded static env."""
        env: Dict[str, ast.expr] = {}
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            pairs: List[Tuple[str, ast.expr]] = []
            if isinstance(v, ast.Call) and \
                    (dotted_name(v.func) or "") == "dict":
                pairs = [(kw.arg, kw.value) for kw in v.keywords if kw.arg]
            elif isinstance(v, ast.Dict):
                pairs = [(k.value, val) for k, val in zip(v.keys, v.values)
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)]
            matched = {k: val for k, val in pairs if k in keys}
            if matched:
                env.update(matched)
        return env


def _is_config_base(base: str) -> bool:
    return (base in ("config", "cfg")
            or base.endswith(".config") or base.endswith(".cfg"))


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

def classify(atom: str, non_hash_fields: Sequence[str]) -> str:
    """covered | leak | incomplete for one provenance atom."""
    if atom.startswith("config:"):
        return "leak" if atom.split(":", 1)[1] in non_hash_fields \
            else "covered"
    if atom.startswith(("unknown:", "api:")):
        return "incomplete"
    return "covered"


def entry_verdict(inputs: Dict[str, Set[str]],
                  non_hash_fields: Sequence[str]) -> str:
    kinds = {classify(a, non_hash_fields)
             for atoms in inputs.values() for a in atoms}
    if "leak" in kinds:
        return "leak"
    if "incomplete" in kinds:
        return "incomplete"
    return "covered"


def build_entry_report(name: str, entry: JitEntry,
                       resolver: ProvenanceResolver,
                       non_hash_fields: Sequence[str],
                       shape_provenance: Sequence[str] = (),
                       notes: Sequence[str] = ()) -> dict:
    prov = resolver.static_provenance(entry)
    inputs = dict(prov)
    if shape_provenance:
        inputs["<dynamic arg shapes+dtypes>"] = set(shape_provenance)
    return {
        "name": name,
        "jit_function": entry.fn.qualname,
        "path": resolver.graph.rel_path(entry.fn.path),
        "line": entry.fn.line,
        "static_argnames": list(entry.static_argnames),
        "donate_argnames": list(entry.donate_argnames),
        "identity_inputs": [
            {"name": k,
             "provenance": sorted(v),
             "classification": _worst(v, non_hash_fields)}
            for k, v in inputs.items()],
        "verdict": entry_verdict(inputs, non_hash_fields),
        "notes": list(notes),
    }


def _worst(atoms: Set[str], non_hash_fields: Sequence[str]) -> str:
    kinds = {classify(a, non_hash_fields) for a in atoms}
    for k in ("leak", "incomplete"):
        if k in kinds:
            return k
    return "covered"


def synthetic_entry_report(name: str, provenance: Sequence[str],
                           non_hash_fields: Sequence[str],
                           anchor_path: str, anchor_line: int,
                           notes: Sequence[str] = ()) -> dict:
    """Report row for an entry whose identity is not a jit decoration
    (the loss structure, the shard_map placement factories)."""
    atoms = set(provenance)
    return {
        "name": name,
        "jit_function": None,
        "path": anchor_path,
        "line": anchor_line,
        "static_argnames": [],
        "donate_argnames": [],
        "identity_inputs": [
            {"name": "<structural identity>",
             "provenance": sorted(atoms),
             "classification": _worst(atoms, non_hash_fields)}],
        "verdict": entry_verdict({"_": atoms}, non_hash_fields),
        "notes": list(notes),
    }
