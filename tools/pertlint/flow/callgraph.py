"""Whole-package call graph + per-function flow summaries.

One parse of every module under the package root produces a
:class:`PackageGraph`:

* functions/methods indexed by dotted qualname, with import-resolved
  call edges;
* per-function **rank/count taint**: values derived from
  ``jax.process_index()`` (or slot 0 of ``process_rank_and_count()``)
  are *rank*-tainted — they DIVERGE across processes; values derived
  from ``jax.process_count()`` (or slot 1) are *count*-tainted — they
  are SPMD-uniform, so ``if jax.process_count() > 1:`` around a
  collective is sound while ``if jax.process_index() == 0:`` is a
  deadlock;
* per-call-site **guard stacks**: the conditional context (if/else
  branch with its taint, except arm, the shadow of a rank-guarded
  early return) each call executes under;
* the **collective-bearing closure**: functions that transitively
  reach a collective primitive (``sync_global_devices``,
  ``process_allgather``, ``broadcast_one_to_all``), so calling
  ``barrier()`` under a rank guard is as much a finding as calling
  the primitive itself.

Everything is stdlib ``ast`` — nothing is imported or executed, so the
same machinery analyses the real package and the seeded-defect test
fixtures alike.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

# dotted-name SUFFIXES that are collective primitives: any path that
# reaches one must be taken by every process in lockstep
COLLECTIVE_ROOTS = (
    "sync_global_devices",
    "process_allgather",
    "broadcast_one_to_all",
)

# host-side fetch of (potentially globally-sharded) array values — the
# FL006 inventory; each call materialises addressable shards only, so
# on >1 process it silently computes on a fraction of the data
HOST_FETCH_RAW = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "device_get",
}

# parameter names that carry a process rank / a process count across a
# function boundary (the package idiom: ``kproc, nproc =
# process_rank_and_count()`` then helpers take one or the other)
RANK_PARAM_NAMES = {"process_index", "proc_index", "kproc", "rank",
                    "host_rank"}
COUNT_PARAM_NAMES = {"process_count", "proc_count", "nproc", "n_proc",
                     "num_processes", "world_size"}

RANK = "rank"
COUNT = "count"
NONE = "none"
UNKNOWN = "unknown"


def dotted_name(expr: ast.expr) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_name(expr.value)
        return f"{base}.{expr.attr}" if base else None
    return None


@dataclasses.dataclass(frozen=True)
class Guard:
    """One conditional frame a statement executes under."""
    kind: str        # "if" | "else" | "except" | "after-return"
    taint: str       # RANK | COUNT | NONE | UNKNOWN
    line: int
    test_text: str
    # for COUNT guards only: which world the guarded branch is —
    # "single" (process_count <= 1 branch) or "multi"; None otherwise
    count_world: Optional[str] = None


@dataclasses.dataclass
class CallSite:
    raw: str                    # the call target as written ("np.asarray")
    resolved: Optional[str]     # package-dotted qualname, or None
    node: ast.Call
    guards: Tuple[Guard, ...]

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclasses.dataclass
class FunctionInfo:
    qualname: str               # module.Class.method / module.func
    module: str
    path: str
    node: ast.AST               # FunctionDef | AsyncFunctionDef
    cls: Optional[str]          # enclosing class name, if a method
    params: List[str]
    # enclosing function's qualname for a nested def (closure scope) —
    # free variables inside the body resolve against this chain
    parent: Optional[str] = None
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    rank_names: Set[str] = dataclasses.field(default_factory=set)
    count_names: Set[str] = dataclasses.field(default_factory=set)

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclasses.dataclass
class ModuleInfo:
    name: str                   # dotted module name
    path: str
    tree: ast.Module
    imports: Dict[str, str]     # local name -> dotted target
    # module-level constant assignments (Name -> value expr)
    constants: Dict[str, ast.expr] = dataclasses.field(default_factory=dict)
    # class attr assignments seen anywhere in the class body/methods:
    # (class, attr) -> [value exprs] — resolves ``self._x`` one level
    class_attrs: Dict[Tuple[str, str], List[ast.expr]] = \
        dataclasses.field(default_factory=dict)
    # top-level defs/classes, for bare-name call resolution
    toplevel: Set[str] = dataclasses.field(default_factory=set)


def _import_map(tree: ast.Module, module: str, package: str
                ) -> Dict[str, str]:
    out: Dict[str, str] = {}
    parts = module.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = \
                    alias.name if alias.asname else alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative: level=1 is the module's own package
                base = parts[:len(parts) - node.level]
                mod = ".".join(base + ([node.module] if node.module else []))
            else:
                mod = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = f"{mod}.{alias.name}" \
                    if mod else alias.name
    return out


class PackageGraph:
    """Parsed view of one package: modules, functions, call edges."""

    def __init__(self, root: pathlib.Path, package: Optional[str] = None):
        self.root = pathlib.Path(root)
        self.package = package or self.root.name
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        self._parse_all()
        self._index_functions()
        self._summarise()
        self.collective_bearing = self._collective_closure()
        self.multiprocess_reachable = self._multiprocess_closure()

    # -- construction -----------------------------------------------------

    def _parse_all(self) -> None:
        for f in sorted(self.root.rglob("*.py")):
            if "__pycache__" in f.parts:
                continue
            rel = f.relative_to(self.root)
            mod_parts = [self.package] + list(rel.parts[:-1])
            stem = rel.stem
            if stem != "__init__":
                mod_parts.append(stem)
            name = ".".join(mod_parts)
            try:
                tree = ast.parse(f.read_text(), filename=str(f))
            except (SyntaxError, UnicodeDecodeError) as exc:
                self.parse_errors.append(
                    (f.as_posix(), f"{type(exc).__name__}: {exc}"))
                continue
            info = ModuleInfo(name=name, path=f.as_posix(), tree=tree,
                              imports=_import_map(tree, name, self.package))
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    info.toplevel.add(node.name)
                elif isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            info.constants[tgt.id] = node.value
                elif isinstance(node, ast.AnnAssign) and node.value and \
                        isinstance(node.target, ast.Name):
                    info.constants[node.target.id] = node.value
            self.modules[name] = info

    def _index_functions(self) -> None:
        for mod in self.modules.values():
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_function(mod, node, cls=None)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self._add_function(mod, sub, cls=node.name)
                    self._collect_class_attrs(mod, node)

    def _add_function(self, mod: ModuleInfo, node, cls: Optional[str],
                      parent: Optional[str] = None) -> None:
        if parent:
            qual = f"{parent}.{node.name}"
        elif cls:
            qual = f"{mod.name}.{cls}.{node.name}"
        else:
            qual = f"{mod.name}.{node.name}"
        a = node.args
        params = [p.arg for p in
                  (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            params.append(a.vararg.arg)
        if a.kwarg:
            params.append(a.kwarg.arg)
        self.functions[qual] = FunctionInfo(
            qualname=qual, module=mod.name, path=mod.path, node=node,
            cls=cls, params=params, parent=parent)
        # nested defs (closures, local callbacks) are indexed under the
        # enclosing function's qualname; they inherit `cls` so a
        # ``self.method(...)`` call inside a closure still resolves.
        # Only direct statement nesting is walked — a def inside a
        # nested ClassDef is out of scope for this analysis.
        for sub in ast.walk(node):
            if sub is node or not isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if self._direct_parent_function(node, sub) is node:
                self._add_function(mod, sub, cls=cls, parent=qual)

    @staticmethod
    def _direct_parent_function(outer, target) -> Optional[ast.AST]:
        """The innermost enclosing function def of ``target`` under
        ``outer`` (``outer`` itself when directly nested)."""
        found = [None]

        def walk(node, owner):
            for child in ast.iter_child_nodes(node):
                if child is target:
                    found[0] = owner
                    return
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    walk(child, child)
                elif not isinstance(child, ast.ClassDef):
                    walk(child, owner)

        walk(outer, outer)
        return found[0]

    def _collect_class_attrs(self, mod: ModuleInfo, cls: ast.ClassDef
                             ) -> None:
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        mod.class_attrs.setdefault(
                            (cls.name, tgt.attr), []).append(node.value)

    # -- name resolution --------------------------------------------------

    def resolve_call(self, raw: Optional[str], fn: FunctionInfo
                     ) -> Optional[str]:
        """Map a written call target to a package qualname, if it is one."""
        if not raw:
            return None
        mod = self.modules[fn.module]
        head, _, rest = raw.partition(".")
        if head == "self" and fn.cls and rest:
            meth = rest.split(".")[0]
            cand = f"{fn.module}.{fn.cls}.{meth}"
            if cand in self.functions:
                return cand
            return None
        # a bare name may be a nested def of this function or of an
        # enclosing one (closure call) — Python scoping: local first
        if not rest:
            scope: Optional[str] = fn.qualname
            while scope is not None:
                cand = f"{scope}.{head}"
                if cand in self.functions:
                    return cand
                scope = self.functions[scope].parent \
                    if scope in self.functions else None
        target = None
        if head in mod.imports:
            target = mod.imports[head] + (f".{rest}" if rest else "")
        elif head in mod.toplevel:
            target = f"{fn.module}.{raw}"
        elif not rest and head in self.functions_in(fn.module):
            target = f"{fn.module}.{head}"
        if target is None:
            return None
        if target in self.functions:
            return target
        # 'pkg.mod.Class.method' / 'pkg.mod.func' via module import
        if target.startswith(self.package + ".") or target == self.package:
            if target in self.functions:
                return target
            # maybe it names a class: Class(...) constructor — map to
            # __init__ so taint flows into the constructor
            init = f"{target}.__init__"
            if init in self.functions:
                return init
        return target if target in self.functions else None

    def functions_in(self, module: str) -> Set[str]:
        return {q.rsplit(".", 1)[1] for q in self.functions
                if self.functions[q].module == module}

    # -- per-function summaries -------------------------------------------

    def _summarise(self) -> None:
        for fn in self.functions.values():
            self._taint_pass(fn)
            self._guard_walk(fn)

    def _taint_pass(self, fn: FunctionInfo) -> None:
        for p in fn.params:
            if p in RANK_PARAM_NAMES:
                fn.rank_names.add(p)
            elif p in COUNT_PARAM_NAMES:
                fn.count_names.add(p)
        # two passes: a later assignment may feed an earlier-read name
        # in loops; the sets only grow, so twice reaches the fixpoint
        # for everything that matters here
        for _ in range(2):
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign):
                    self._taint_assign(fn, node.targets, node.value)
                elif isinstance(node, ast.AnnAssign) and node.value:
                    self._taint_assign(fn, [node.target], node.value)
                elif isinstance(node, ast.AugAssign):
                    self._taint_assign(fn, [node.target], node.value)

    def _taint_assign(self, fn: FunctionInfo, targets, value) -> None:
        # rank/count tuple unpack: a, b = process_rank_and_count()
        if (isinstance(value, ast.Call)
                and (dotted_name(value.func) or "").endswith(
                    "process_rank_and_count")):
            for tgt in targets:
                if isinstance(tgt, (ast.Tuple, ast.List)) \
                        and len(tgt.elts) == 2:
                    if isinstance(tgt.elts[0], ast.Name):
                        fn.rank_names.add(tgt.elts[0].id)
                    if isinstance(tgt.elts[1], ast.Name):
                        fn.count_names.add(tgt.elts[1].id)
                elif isinstance(tgt, ast.Name):
                    fn.rank_names.add(tgt.id)   # whole tuple: divergent
            return
        t = self.expr_taint(value, fn)
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                if t == RANK:
                    fn.rank_names.add(tgt.id)
                elif t == COUNT:
                    fn.count_names.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)) and t == RANK:
                for e in tgt.elts:
                    if isinstance(e, ast.Name):
                        fn.rank_names.add(e.id)

    def expr_taint(self, expr: ast.expr, fn: FunctionInfo) -> str:
        """RANK if the value diverges across processes, COUNT if it is
        the (uniform) world size, NONE if process-independent."""
        if isinstance(expr, ast.Call):
            raw = dotted_name(expr.func) or ""
            if raw.endswith("process_index"):
                return RANK
            if raw.endswith("process_count"):
                return COUNT
            if raw.endswith("process_rank_and_count"):
                return RANK          # the tuple itself: divergent part
            sub = [self.expr_taint(a, fn) for a in expr.args] + \
                  [self.expr_taint(k.value, fn) for k in expr.keywords]
            return _join(sub)
        if isinstance(expr, ast.Name):
            if expr.id in fn.rank_names:
                return RANK
            if expr.id in fn.count_names:
                return COUNT
            return NONE
        if isinstance(expr, ast.Attribute):
            if expr.attr == "process_index":
                return RANK
            if expr.attr == "process_count":
                return COUNT
            return NONE
        if isinstance(expr, ast.Constant):
            return NONE
        if isinstance(expr, (ast.Compare, ast.BoolOp, ast.BinOp,
                             ast.UnaryOp, ast.IfExp)):
            return _join([self.expr_taint(c, fn) for c in
                          ast.iter_child_nodes(expr)
                          if isinstance(c, ast.expr)])
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return _join([self.expr_taint(e, fn) for e in expr.elts])
        if isinstance(expr, ast.Subscript):
            return self.expr_taint(expr.value, fn)
        return NONE

    def _count_world(self, test: ast.expr, fn: FunctionInfo
                     ) -> Optional[str]:
        """For a COUNT-tainted comparison: does the TRUE branch mean a
        single-process world ('count <= 1') or a multi-process one
        ('count > 1')?  None when the pattern is not recognised."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = self._count_world(test.operand, fn)
            return {"single": "multi", "multi": "single"}.get(inner or "")
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and len(test.comparators) == 1):
            return None
        left, op, right = test.left, test.ops[0], test.comparators[0]
        if self.expr_taint(right, fn) == COUNT and \
                isinstance(left, ast.Constant):
            # normalise '1 < count' to 'count > 1' etc.
            left, right = right, left
            op = {ast.Lt: ast.Gt, ast.Gt: ast.Lt,
                  ast.LtE: ast.GtE, ast.GtE: ast.LtE}.get(type(op),
                                                          type(op))()
        if self.expr_taint(left, fn) != COUNT or \
                not isinstance(right, ast.Constant):
            return None
        v = right.value
        if not isinstance(v, int):
            return None
        if isinstance(op, ast.LtE) and v == 1 or \
                isinstance(op, ast.Lt) and v == 2 or \
                isinstance(op, ast.Eq) and v == 1:
            return "single"
        if isinstance(op, ast.Gt) and v == 1 or \
                isinstance(op, ast.GtE) and v == 2 or \
                isinstance(op, ast.NotEq) and v == 1:
            return "multi"
        return None

    def _guard_walk(self, fn: FunctionInfo) -> None:
        src_seg = getattr(ast, "unparse", None)

        def text(node) -> str:
            try:
                return src_seg(node) if src_seg else "<cond>"
            except Exception:  # noqa: BLE001 — display only
                return "<cond>"

        def record_calls(node: ast.AST, guards: Tuple[Guard, ...]) -> None:
            # record calls of this statement WITHOUT descending into
            # nested statement-bearing constructs (handled by walk)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    raw = dotted_name(sub.func) or ""
                    fn.calls.append(CallSite(
                        raw=raw, resolved=self.resolve_call(raw, fn),
                        node=sub, guards=guards))

        def terminal(stmts: List[ast.stmt]) -> bool:
            return bool(stmts) and isinstance(
                stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

        def walk(stmts: List[ast.stmt], guards: Tuple[Guard, ...]) -> None:
            shadow = guards
            for s in stmts:
                if isinstance(s, ast.If):
                    record_calls(s.test, shadow)
                    t = self.expr_taint(s.test, fn)
                    world = self._count_world(s.test, fn) \
                        if t == COUNT else None
                    g_if = Guard("if", t, s.lineno, text(s.test), world)
                    g_el = Guard("else", t, s.lineno, text(s.test),
                                 {"single": "multi",
                                  "multi": "single"}.get(world or ""))
                    walk(s.body, shadow + (g_if,))
                    walk(s.orelse, shadow + (g_el,))
                    if t == RANK and (terminal(s.body)
                                      or terminal(s.orelse)):
                        # a rank-guarded early return splits the world:
                        # everything after runs on a rank subset
                        shadow = shadow + (Guard(
                            "after-return", RANK, s.lineno, text(s.test)),)
                elif isinstance(s, ast.Try):
                    walk(s.body, shadow)
                    for h in s.handlers:
                        walk(h.body, shadow + (Guard(
                            "except", UNKNOWN, h.lineno,
                            text(h.type) if h.type else "Exception"),))
                    walk(s.orelse, shadow)
                    walk(s.finalbody, shadow)
                elif isinstance(s, (ast.For, ast.AsyncFor)):
                    record_calls(s.iter, shadow)
                    walk(s.body, shadow)
                    walk(s.orelse, shadow)
                elif isinstance(s, (ast.While,)):
                    record_calls(s.test, shadow)
                    walk(s.body, shadow)
                    walk(s.orelse, shadow)
                elif isinstance(s, (ast.With, ast.AsyncWith)):
                    for item in s.items:
                        record_calls(item.context_expr, shadow)
                    walk(s.body, shadow)
                elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    # nested defs are indexed separately; a def is not
                    # a call — its body's guards start fresh there
                    continue
                else:
                    record_calls(s, shadow)

        walk(fn.node.body, ())

    # -- closures ---------------------------------------------------------

    def _is_collective_root(self, site: CallSite) -> bool:
        return any(site.raw.endswith(root) for root in COLLECTIVE_ROOTS)

    def _collective_closure(self) -> Set[str]:
        bearing: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for fn in self.functions.values():
                if fn.qualname in bearing:
                    continue
                for site in fn.calls:
                    if self._is_collective_root(site) or \
                            (site.resolved in bearing):
                        bearing.add(fn.qualname)
                        changed = True
                        break
        return bearing

    def _multiprocess_closure(self) -> Set[str]:
        """Functions that run during a multi-host run: the collective-
        bearing set plus everything they (transitively) call — the
        scope of the FL006 host-fetch inventory."""
        reach: Set[str] = set(self.collective_bearing)
        work = list(reach)
        while work:
            q = work.pop()
            fn = self.functions.get(q)
            if fn is None:
                continue
            for site in fn.calls:
                tgt = site.resolved
                if tgt and tgt in self.functions and tgt not in reach:
                    reach.add(tgt)
                    work.append(tgt)
        return reach

    # -- queries the rules use --------------------------------------------

    def collective_sites(self, fn: FunctionInfo) -> List[CallSite]:
        """Call sites in ``fn`` that issue (or reach) a collective."""
        return [s for s in fn.calls
                if self._is_collective_root(s)
                or (s.resolved in self.collective_bearing)]

    def host_fetch_sites(self, fn: FunctionInfo) -> List[CallSite]:
        return [s for s in fn.calls if s.raw in HOST_FETCH_RAW]

    def rel_path(self, path: str) -> str:
        """Path as findings should report it: relative to the repo when
        under cwd, else as parsed."""
        p = pathlib.Path(path)
        try:
            return p.relative_to(pathlib.Path.cwd()).as_posix()
        except ValueError:
            return p.as_posix()


def _join(taints: Sequence[str]) -> str:
    if RANK in taints:
        return RANK
    if UNKNOWN in taints:
        return UNKNOWN
    if COUNT in taints:
        return COUNT
    return NONE


def build_graph(root: pathlib.Path, package: Optional[str] = None
                ) -> PackageGraph:
    return PackageGraph(root, package)
