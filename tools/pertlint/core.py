"""Rule registry and the Finding record every rule emits.

A rule is a class with a stable ``id`` (``PLnnn`` for the AST layer,
``DPnnn`` for the deep jaxpr/sharding layer), a ``severity`` (``error``
gates the build; ``warning`` is reported but never flips the exit code
on its own — the knob exists so a new rule can soak before it gates),
and a ``check(ctx)`` generator yielding :class:`Finding`.  Registration
is a decorator so each rule module is self-contained and
``rules/__init__.py`` only has to import them.

Three rule KINDS share the registry:

* ``ast`` (PLnnn) — pure-stdlib source-text rules; ``check`` receives an
  ``engine.FileContext``;
* ``deep`` (DPnnn) — semantic rules over traced programs; ``check``
  receives a context built by ``tools.pertlint.deep.engine`` (a
  ``ProgramContext`` per jit entry point, or the layout contract).  The
  deep rule CLASSES are stdlib-importable (jax is imported only when a
  deep check actually runs) so ``--list-rules`` works without jax;
* ``flow`` (FLnnn) — interprocedural rules over the whole-package call
  graph (SPMD collective discipline, config-to-jit program-identity
  dataflow); ``check`` receives a ``FlowContext`` built by
  ``tools.pertlint.flow.engine``.  Pure stdlib end to end — the flow
  layer parses, it never imports the analysed package.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Type

SEVERITIES = ("error", "warning")
KINDS = ("ast", "deep", "flow")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str       # "PL001" / "DP003" / "FL001"
    severity: str   # "error" | "warning"
    path: str       # posix path as given to the engine (repo-relative in CI)
    line: int       # 1-based, the AST node's lineno
    col: int        # 0-based
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")


class Rule:
    """Base class; subclasses set the class attributes and ``check``."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""
    kind: str = "ast"

    def check(self, ctx) -> Iterable[Finding]:  # ctx: engine.FileContext
        raise NotImplementedError

    def finding(self, ctx, node, message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity, path=ctx.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), message=message)


_REGISTRY: Dict[str, Type[Rule]] = {}

_PREFIX_BY_KIND = {"ast": "PL", "deep": "DP", "flow": "FL"}


def register(cls: Type[Rule]) -> Type[Rule]:
    if cls.kind not in KINDS:
        raise ValueError(f"rule {cls.__name__}: bad kind {cls.kind!r}")
    prefix = _PREFIX_BY_KIND[cls.kind]
    if not cls.id or not cls.id.startswith(prefix):
        raise ValueError(f"rule {cls.__name__} ({cls.kind}) needs a "
                         f"{prefix}nnn id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules(kind: Optional[str] = "ast") -> List[Rule]:
    """Fresh instances of every registered rule of ``kind``, id-ordered.

    Default is the AST layer — the engine's and tests' historical
    contract.  ``kind='deep'`` returns the jaxpr/sharding rules,
    ``kind='flow'`` the interprocedural call-graph rules;
    ``kind=None`` returns all three (the CLI's ``--list-rules``).
    Importing any rule package is stdlib-only.
    """
    import tools.pertlint.rules  # noqa: F401 — importing registers them
    import tools.pertlint.deep.rules_jaxpr  # noqa: F401
    import tools.pertlint.deep.rules_sharding  # noqa: F401
    import tools.pertlint.flow.rules_flow  # noqa: F401
    return [_REGISTRY[rid]() for rid in sorted(_REGISTRY)
            if kind is None or _REGISTRY[rid].kind == kind]
