"""Rule registry and the Finding record every rule emits.

A rule is a class with a stable ``id`` (``PLnnn``), a ``severity``
(``error`` gates the build; ``warning`` is reported but never flips the
exit code on its own — the knob exists so a new rule can soak before it
gates), and a ``check(ctx)`` generator yielding :class:`Finding`.
Registration is a decorator so each rule module is self-contained and
``rules/__init__.py`` only has to import them.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Type

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str       # "PL001"
    severity: str   # "error" | "warning"
    path: str       # posix path as given to the engine (repo-relative in CI)
    line: int       # 1-based, the AST node's lineno
    col: int        # 0-based
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")


class Rule:
    """Base class; subclasses set the class attributes and ``check``."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx) -> Iterable[Finding]:  # ctx: engine.FileContext
        raise NotImplementedError

    def finding(self, ctx, node, message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity, path=ctx.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), message=message)


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id or not cls.id.startswith("PL"):
        raise ValueError(f"rule {cls.__name__} needs a PLnnn id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> List[Rule]:
    """Fresh instances of every registered rule, id-ordered."""
    import tools.pertlint.rules  # noqa: F401 — importing registers them
    return [_REGISTRY[rid]() for rid in sorted(_REGISTRY)]
