"""Cross-run fleet index: turn individual RunLog JSONLs into trends and
CI regression gates.

Every telemetry-enabled run leaves one JSONL artifact, and until now
those artifacts died where they were written — nothing in the repo
could say "fit wall has crept up 30% over the last five rounds".  This
tool closes that gap:

    python -m tools.pert_fleet index   [--roots DIR ...] [--out FILE]
    python -m tools.pert_fleet query   [--config-hash H] [--run-name N]
                                       [--status S] [--request ID|*]
                                       [--since D] [--until D]
                                       [--format markdown|json]
    python -m tools.pert_fleet trend   [--metric M ...] [--request ID|*]
                                       [--out FILE]
                                       [--format markdown|json]
    python -m tools.pert_fleet regress --baseline FILE [--run LOG]
                                       [--tolerance-scale S]
                                       [--write-baseline FILE]

Serve traffic rides the same machinery: pointing ``index --roots`` at
a pert-serve spool directory ingests the worker log AND every
per-request RunLog under its ``results/`` tree (they are ordinary
``*.jsonl`` run logs, stamped with a ``request_id``), and ``query`` /
``trend --request`` group on that id — ``--request '*'`` keeps every
request-stamped run, a literal id keeps one request's runs.

* ``index`` ingests every run log under the roots (default: the
  repo-local ``.pert_runs/`` plus ``artifacts/``) into one queryable
  JSON index — per run: identity (config hash, platform, workload
  shape), status, and the flat metric vector from
  ``obs.summary.flat_metrics`` (the final ``metrics_snapshot`` overlaid
  on metrics derived from standard events, so pre-v5 logs index too);
* ``query`` filters the index (config hash / date window / run name /
  status) and prints a markdown table;
* ``trend`` renders, per metric, a markdown table plus a unicode
  sparkline across runs in time order — the bench trajectory as one
  glance;
* ``regress`` compares one run (``--run``, or the newest indexed run)
  against a committed baseline artifact, applying each metric's
  relative threshold from ``obs/metrics_manifest.json`` (direction-
  aware: only movement in the BAD direction fails).  Nonzero exit on
  any gated regression — the CI gate.  ``--tolerance-scale`` widens
  every threshold by a factor (the CI job compares across machines,
  where wall-clock thresholds tuned for same-machine A/Bs would
  flake); ``--write-baseline`` records the run as the new baseline
  instead of comparing.  Metrics in a baseline that the manifest does
  not know are warned about and skipped, never silently gated.

Pure stdlib + the obs package — runnable without jax, like
``tools/pert_report.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time
from typing import List, Optional

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from scdna_replication_tools_tpu.obs.metrics import (  # noqa: E402
    manifest_metrics,
    metric_base_name,
    regress_verdict,
)
from scdna_replication_tools_tpu.obs.summary import (  # noqa: E402
    flat_metrics,
    summarize_run,
)

DEFAULT_ROOTS = (".pert_runs", "artifacts")
DEFAULT_INDEX = ".pert_runs/fleet_index.json"

_SPARK_BARS = "▁▂▃▄▅▆▇█"


def _warn(msg: str) -> None:
    print(f"pert_fleet: warning: {msg}", file=sys.stderr)


# ---------------------------------------------------------------------------
# per-run extraction + the index
# ---------------------------------------------------------------------------


def run_record(path) -> Optional[dict]:
    """One index record for a run-log file; None when unreadable or not
    a run log (no run_start envelope)."""
    path = pathlib.Path(path)
    summary = summarize_run(path)
    if summary is None or summary.get("run_name") is None:
        return None
    fits = summary.get("fits") or []
    cells = [f.get("num_cells") for f in fits
             if isinstance(f.get("num_cells"), int)]
    try:
        mtime = path.stat().st_mtime
    except OSError:
        mtime = None
    # the cost plane (schema v9 run_end.meter): attributed device time
    # and goodput, promoted to top-level record fields so `query
    # --format json` answers "what did this run cost" without a
    # re-parse (ISSUE acceptance: the fleet surface of the meter)
    meter = summary.get("meter") or {}
    return {
        "path": str(path),
        "file": path.name,
        "mtime": mtime,
        "run_name": summary.get("run_name"),
        # serve traffic (schema v7): per-request RunLogs under the
        # worker's spool/results tree carry the request id in
        # run_start — `query`/`trend` group on it via --request
        "request_id": summary.get("request_id"),
        "schema_version": summary.get("schema_version"),
        "started_unix": summary.get("started_unix"),
        "config_hash": summary.get("config_hash"),
        "platform": summary.get("platform"),
        "device_kind": summary.get("device_kind"),
        "num_devices": summary.get("num_devices"),
        "status": summary.get("status"),
        "wall_seconds": summary.get("wall_seconds"),
        "device_seconds": meter.get("billed_device_seconds"),
        "goodput": meter.get("goodput_cell_iters_per_device_second"),
        "waste_frac": meter.get("waste_frac"),
        "workload": {
            "num_cells": max(cells) if cells else None,
            "steps": sorted({str(f.get("step")) for f in fits
                             if f.get("step")}),
        },
        "metrics": flat_metrics(summary),
    }


def discover_logs(roots) -> List[pathlib.Path]:
    found: List[pathlib.Path] = []
    for root in roots:
        root = pathlib.Path(root)
        if root.is_file():
            found.append(root)
        elif root.is_dir():
            found.extend(sorted(root.rglob("*.jsonl")))
    # dedupe, keep discovery order
    seen = set()
    out = []
    for p in found:
        key = str(p.resolve())
        if key not in seen:
            seen.add(key)
            out.append(p)
    return out


def build_index(roots) -> dict:
    runs = []
    for path in discover_logs(roots):
        record = run_record(path)
        if record is None:
            _warn(f"{path}: not a readable run log — skipped")
            continue
        runs.append(record)
    runs.sort(key=_run_time)
    return {
        "kind": "pert_fleet_index",
        "generated_unix": round(time.time(), 3),
        "roots": [str(r) for r in roots],
        "num_runs": len(runs),
        "runs": runs,
    }


def _run_time(record: dict) -> float:
    t = record.get("started_unix")
    if isinstance(t, (int, float)):
        return float(t)
    return float(record.get("mtime") or 0.0)


def load_runs(args) -> List[dict]:
    """Runs for query/trend/regress: from ``--index`` when it exists,
    else indexed fresh from the roots."""
    index_path = pathlib.Path(args.index)
    if index_path.is_file():
        try:
            doc = json.loads(index_path.read_text())
            return list(doc.get("runs", []))
        except (OSError, ValueError) as exc:
            _warn(f"unreadable index {index_path} ({exc}); re-indexing")
    return build_index(args.roots)["runs"]


def filter_runs(runs: List[dict], args) -> List[dict]:
    def _date(value):
        return time.mktime(time.strptime(value, "%Y-%m-%d"))

    out = runs
    if getattr(args, "config_hash", None):
        out = [r for r in out if r.get("config_hash") == args.config_hash]
    if getattr(args, "run_name", None):
        out = [r for r in out if r.get("run_name") == args.run_name]
    if getattr(args, "request", None):
        # '*' keeps every run that IS a request (serve traffic only);
        # a literal id keeps that request's runs
        if args.request == "*":
            out = [r for r in out if r.get("request_id")]
        else:
            out = [r for r in out if r.get("request_id") == args.request]
    if getattr(args, "status", None):
        out = [r for r in out if r.get("status") == args.status]
    if getattr(args, "since", None):
        out = [r for r in out if _run_time(r) >= _date(args.since)]
    if getattr(args, "until", None):
        # inclusive day: anything before the NEXT midnight
        out = [r for r in out
               if _run_time(r) < _date(args.until) + 86400.0]
    return sorted(out, key=_run_time)


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_time(record: dict) -> str:
    t = _run_time(record)
    if not t:
        return "-"
    return time.strftime("%Y-%m-%d %H:%M", time.localtime(t))


def _fmt_val(value) -> str:
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.4g}"
    return str(value)


def sparkline(values) -> str:
    """Unicode sparkline; non-numeric entries render as '·'."""
    nums = [v for v in values if isinstance(v, (int, float))]
    if not nums:
        return ""
    lo, hi = min(nums), max(nums)
    out = []
    for v in values:
        if not isinstance(v, (int, float)):
            out.append("·")
        elif hi == lo:
            out.append(_SPARK_BARS[3])
        else:
            idx = int((v - lo) / (hi - lo) * (len(_SPARK_BARS) - 1)
                      + 0.5)
            out.append(_SPARK_BARS[idx])
    return "".join(out)


def render_query(runs: List[dict]) -> str:
    lines = ["| run | when | status | platform | config | request | "
             "cells | wall (s) |",
             "|---|---|---|---|---|---|---:|---:|"]
    for r in runs:
        lines.append(
            f"| `{r.get('file')}` | {_fmt_time(r)} | {r.get('status')} "
            f"| {r.get('platform') or '-'} "
            f"| `{r.get('config_hash') or '-'}` "
            f"| {r.get('request_id') or '-'} "
            f"| {_fmt_val((r.get('workload') or {}).get('num_cells'))} "
            f"| {_fmt_val(r.get('wall_seconds'))} |")
    return "\n".join(lines)


def default_trend_metrics() -> List[str]:
    """Gated metrics first (the bench trajectory), in manifest order."""
    return [name for name, spec in manifest_metrics().items()
            if spec.get("regress")]


def trend_document(runs: List[dict], metric_names: List[str]) -> dict:
    """Machine-readable twin of :func:`render_trend` (``trend --format
    json``): per metric, the manifest spec plus the time-ordered value
    series — the interface the cross-run autopilot (ROADMAP item 5)
    consumes instead of re-parsing markdown."""
    known = manifest_metrics()
    metrics: dict = {}
    for name in metric_names:
        values = [(r.get("metrics") or {}).get(name) for r in runs]
        if not any(isinstance(v, (int, float)) for v in values):
            continue
        spec = known.get(name, {})
        metrics[name] = {
            "help": spec.get("help"),
            "regress": spec.get("regress"),
            "values": values,
            "runs": [{"file": r.get("file"),
                      "when_unix": _run_time(r) or None,
                      "config_hash": r.get("config_hash"),
                      "value": v}
                     for r, v in zip(runs, values)],
        }
    return {"kind": "pert_fleet_trend", "num_runs": len(runs),
            "metrics": metrics}


def render_trend(runs: List[dict], metric_names: List[str]) -> str:
    lines = [f"# PERT fleet trend — {len(runs)} run(s)", ""]
    if not runs:
        return "\n".join(lines + ["_no indexed runs_", ""])
    known = manifest_metrics()
    for name in metric_names:
        values = [(r.get("metrics") or {}).get(name) for r in runs]
        if not any(isinstance(v, (int, float)) for v in values):
            continue
        spec = known.get(name, {})
        lines.append(f"## `{name}`")
        if spec.get("help"):
            lines.append(f"_{spec['help']}_")
        lines.append("")
        lines.append(f"`{sparkline(values)}`")
        lines.append("")
        lines += ["| run | when | value |", "|---|---|---:|"]
        for r, v in zip(runs, values):
            lines.append(f"| `{r.get('file')}` | {_fmt_time(r)} "
                         f"| {_fmt_val(v)} |")
        lines.append("")
    if len(lines) == 2:
        lines += ["_none of the requested metrics appear in the indexed "
                  "runs_", ""]
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# regress
# ---------------------------------------------------------------------------


# re-exported for callers/tests that think in fleet terms; the one
# implementation lives with the manifest (obs/metrics.py)
_metric_base_name = metric_base_name


def write_baseline(record: dict, out_path) -> dict:
    doc = {
        "kind": "pert_fleet_baseline",
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "run_log": record.get("file"),
        "platform": record.get("platform"),
        "device_kind": record.get("device_kind"),
        "config_hash": record.get("config_hash"),
        "workload": record.get("workload"),
        "note": "pert_fleet regression baseline: HEAD runs are compared "
                "against these metrics with the per-metric relative "
                "thresholds from obs/metrics_manifest.json; refresh "
                "with `python -m tools.pert_fleet regress --run RUN "
                "--write-baseline <this file>`",
        "metrics": record.get("metrics") or {},
    }
    out_path = pathlib.Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    out_path.write_text(json.dumps(doc, indent=1, sort_keys=False)
                        + "\n")
    return doc


def compare_to_baseline(baseline: dict, record: dict,
                        tolerance_scale: float = 1.0) -> dict:
    """Per-metric comparison of one run against a baseline artifact.

    Returns ``{"rows": [...], "regressions": [...], "warnings": [...]}``
    — a row per baseline metric with the applied threshold and verdict
    from the SHARED judgement ``obs.metrics.regress_verdict`` (the same
    vocabulary ``pert_report --compare`` renders):

    * ``REGRESSED`` — moved in the bad direction past the (scaled,
      direction-capped) threshold; drives the nonzero exit;
    * ``ok`` / ``improved`` — within threshold / moved the good way
      past it;
    * ``incomparable`` — zero baseline moved the bad way: the relative
      delta is infinite, so it is warned about, never hard-gated (a
      warm-cache baseline with 0 compile misses must not wedge CI);
    * ``untracked`` — compared for the record, but the manifest arms no
      regress gate for it;
    * ``missing`` — the run lacks the metric (warned, not failed: a
      degraded run already fails louder elsewhere).
    """
    known = manifest_metrics()
    run_metrics = record.get("metrics") or {}
    rows, regressions, warnings = [], [], []
    for key in sorted((baseline.get("metrics") or {})):
        base_val = baseline["metrics"][key]
        if not isinstance(base_val, (int, float)):
            continue
        spec = known.get(metric_base_name(key))
        if spec is None:
            warnings.append(
                f"baseline metric {key!r} is not in "
                f"obs/metrics_manifest.json — skipped (register it, or "
                f"refresh the baseline)")
            continue
        run_val = run_metrics.get(key)
        if not isinstance(run_val, (int, float)):
            warnings.append(f"run lacks baseline metric {key!r}")
            rows.append({"metric": key, "baseline": base_val,
                         "run": None, "rel_delta": None,
                         "threshold": None, "verdict": "missing"})
            continue
        rel, threshold, verdict = regress_verdict(
            spec, base_val, run_val, tolerance_scale=tolerance_scale)
        if verdict == "incomparable":
            warnings.append(
                f"baseline metric {key!r} is 0 — relative regression "
                f"gating is undefined from a zero base; refresh the "
                f"baseline from a comparable run")
        row = {"metric": key, "baseline": base_val, "run": run_val,
               "rel_delta": rel, "threshold": threshold,
               "direction": (spec.get("regress") or {}).get("direction"),
               "verdict": verdict}
        rows.append(row)
        if verdict == "REGRESSED":
            regressions.append(row)
    return {"rows": rows, "regressions": regressions,
            "warnings": warnings}


def render_regress(baseline: dict, record: dict, result: dict,
                   tolerance_scale: float) -> str:
    lines = [
        "# PERT fleet regression gate",
        "",
        f"- **baseline**: `{baseline.get('run_log')}` "
        f"({baseline.get('created')}, {baseline.get('platform')}, "
        f"config `{baseline.get('config_hash')}`)",
        f"- **run**: `{record.get('file')}` ({_fmt_time(record)}, "
        f"{record.get('platform')}, config "
        f"`{record.get('config_hash')}`)",
        f"- **tolerance scale**: x{tolerance_scale:g}",
        f"- **verdict**: "
        + ("**REGRESSED** — "
           f"{len(result['regressions'])} gated metric(s) over "
           "threshold" if result["regressions"] else "clean"),
        "",
        "| metric | baseline | run | Δ rel | threshold | verdict |",
        "|---|---:|---:|---:|---:|---|",
    ]
    for row in result["rows"]:
        rel = row.get("rel_delta")
        thr = row.get("threshold")
        mark = {"REGRESSED": "⚠ **REGRESSED**"}.get(row["verdict"],
                                                    row["verdict"])
        lines.append(
            f"| `{row['metric']}` | {_fmt_val(row['baseline'])} "
            f"| {_fmt_val(row.get('run'))} "
            f"| {'-' if rel is None or not _finite(rel) else f'{rel:+.1%}'} "
            f"| {'-' if thr is None else f'±{thr:.0%}'} | {mark} |")
    for w in result["warnings"]:
        lines.append(f"- warning: {w}")
    lines.append("")
    return "\n".join(lines)


def _finite(value) -> bool:
    return isinstance(value, (int, float)) \
        and value == value and abs(value) != float("inf")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _add_source_args(ap) -> None:
    ap.add_argument("--roots", nargs="+", default=list(DEFAULT_ROOTS),
                    help="directories (or run-log files) to ingest "
                         "(default: .pert_runs/ + artifacts/)")
    ap.add_argument("--index", default=DEFAULT_INDEX,
                    help="existing index file to read instead of "
                         "re-scanning the roots (built with the 'index' "
                         "subcommand)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pert_fleet",
        description="Cross-run fleet index over RunLog JSONLs: index, "
                    "query, trend, and the CI regression gate")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_index = sub.add_parser("index", help="ingest run logs into one "
                                           "queryable index file")
    p_index.add_argument("--roots", nargs="+",
                         default=list(DEFAULT_ROOTS))
    p_index.add_argument("--out", default=DEFAULT_INDEX)

    p_query = sub.add_parser("query", help="filter + list indexed runs")
    _add_source_args(p_query)
    p_query.add_argument("--config-hash", default=None)
    p_query.add_argument("--run-name", default=None)
    p_query.add_argument("--status", default=None)
    p_query.add_argument("--request", default=None, metavar="ID",
                         help="keep only serve-request runs: a request "
                              "id, or '*' for every run that carries "
                              "one (per-request RunLogs under a "
                              "pert-serve spool/results tree)")
    p_query.add_argument("--since", default=None, metavar="YYYY-MM-DD")
    p_query.add_argument("--until", default=None, metavar="YYYY-MM-DD")
    p_query.add_argument("--format", default="markdown",
                         choices=("markdown", "json"),
                         help="output format: the markdown table "
                              "(default) or the matching records as "
                              "JSON (machine-readable; the autopilot/"
                              "scripting interface)")
    p_query.add_argument("--json", action="store_true",
                         help="alias for --format json")

    p_trend = sub.add_parser("trend", help="markdown table + sparkline "
                                           "per metric across runs")
    _add_source_args(p_trend)
    p_trend.add_argument("--config-hash", default=None)
    p_trend.add_argument("--run-name", default=None)
    p_trend.add_argument("--status", default=None)
    p_trend.add_argument("--request", default=None, metavar="ID",
                         help="trend serve-request runs only: a "
                              "request id, or '*' for every run that "
                              "carries one")
    p_trend.add_argument("--since", default=None, metavar="YYYY-MM-DD")
    p_trend.add_argument("--until", default=None, metavar="YYYY-MM-DD")
    p_trend.add_argument("--metric", nargs="+", default=None,
                         help="metric names/series keys to trend "
                              "(default: every manifest metric with a "
                              "regress gate)")
    p_trend.add_argument("--format", default="markdown",
                         choices=("markdown", "json"),
                         help="output format: markdown + sparklines "
                              "(default) or a JSON document of "
                              "per-metric value series (machine-"
                              "readable; the autopilot/scripting "
                              "interface)")
    p_trend.add_argument("--out", default=None,
                         help="write the report here instead of stdout")

    p_reg = sub.add_parser(
        "regress",
        help="compare one run against a committed baseline; nonzero "
             "exit on any gated regression")
    _add_source_args(p_reg)
    p_reg.add_argument("--baseline", default=None,
                       help="baseline artifact (e.g. "
                            "artifacts/FLEET_BASELINE_cpu.json); "
                            "required unless --write-baseline")
    p_reg.add_argument("--run", default=None,
                       help="run log to gate (default: the newest "
                            "indexed run)")
    p_reg.add_argument("--tolerance-scale", type=float, default=1.0,
                       help="multiply every manifest threshold by this "
                            "factor (CI compares across machines, where "
                            "same-machine wall thresholds would flake)")
    p_reg.add_argument("--write-baseline", default=None, metavar="FILE",
                       help="record the run as the new baseline instead "
                            "of comparing")
    p_reg.add_argument("--out", default=None,
                       help="write the markdown verdict here too")

    args = ap.parse_args(argv)

    if args.cmd == "index":
        index = build_index(args.roots)
        out = pathlib.Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(index, indent=1) + "\n")
        print(f"pert_fleet: indexed {index['num_runs']} run(s) from "
              f"{', '.join(index['roots'])} -> {out}")
        return 0

    if args.cmd == "query":
        runs = filter_runs(load_runs(args), args)
        if args.json or args.format == "json":
            print(json.dumps(runs, indent=1))
        else:
            print(render_query(runs))
        return 0

    if args.cmd == "trend":
        runs = filter_runs(load_runs(args), args)
        metrics = args.metric or default_trend_metrics()
        if args.format == "json":
            report = json.dumps(trend_document(runs, metrics), indent=1)
        else:
            report = render_trend(runs, metrics)
        if args.out:
            pathlib.Path(args.out).write_text(report + "\n")
        else:
            print(report)
        return 0

    # regress
    if args.run:
        record = run_record(args.run)
        if record is None:
            raise SystemExit(f"pert_fleet: {args.run} is not a readable "
                             f"run log")
    else:
        runs = sorted(load_runs(args), key=_run_time)
        if not runs:
            raise SystemExit("pert_fleet: no indexed runs to gate — "
                             "pass --run or build an index first")
        record = runs[-1]

    if args.write_baseline:
        write_baseline(record, args.write_baseline)
        print(f"pert_fleet: baseline written to {args.write_baseline} "
              f"from {record.get('file')}")
        return 0

    if not args.baseline:
        raise SystemExit("pert_fleet: regress needs --baseline FILE "
                         "(or --write-baseline to record one)")
    try:
        baseline = json.loads(pathlib.Path(args.baseline).read_text())
    except (OSError, ValueError) as exc:
        raise SystemExit(f"pert_fleet: unreadable baseline "
                         f"{args.baseline} ({exc})")
    result = compare_to_baseline(baseline, record,
                                 tolerance_scale=args.tolerance_scale)
    for w in result["warnings"]:
        _warn(w)
    report = render_regress(baseline, record, result,
                            args.tolerance_scale)
    if args.out:
        pathlib.Path(args.out).write_text(report + "\n")
    print(report)
    if result["regressions"]:
        names = ", ".join(r["metric"] for r in result["regressions"])
        print(f"pert_fleet: REGRESSION GATE FAILED: {names}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `pert_fleet trend | head` is normal usage
        sys.exit(0)
