"""Simulate-and-recover accuracy sweep: PERT vs generative truth.

The reference's only accuracy evidence is visual notebook inspection
(SURVEY.md §4); this tool quantifies recovery on the simulator's own
ground truth across coverage levels — the testing idiom SURVEY
recommends, as a committed artifact.  For each configuration it
simulates a 2-clone chr1 workload (``pert_simulator``), runs the full
``scRT.infer('pert')`` pipeline, and records:

* ``rep_accuracy``   — per-bin replication-state agreement with true_rep
* ``cn_accuracy``    — per-bin CN-state agreement with true_somatic_cn
* ``tau_corr``       — Pearson r of fitted model_tau vs generative true_t
* ``lambda_abs_err`` — |model_lambda − simulated lambda|

Writes one JSON artifact (--out).  CPU-runnable in a few minutes at the
default sizes; the metrics are hardware-independent.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
# sibling tools are importable too (force_cpu_backend lives in
# full_pipeline_bench)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))


_TUTORIAL_MOD = None


def _tutorial():
    """Import examples/tutorial.py (not a package) for its frame builder,
    once — re-executing it per config would stack duplicate sys.path
    entries from its module body."""
    global _TUTORIAL_MOD
    if _TUTORIAL_MOD is None:
        path = (pathlib.Path(__file__).resolve().parents[1]
                / "examples" / "tutorial.py")
        spec = importlib.util.spec_from_file_location("pert_tutorial", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _TUTORIAL_MOD = mod
    return _TUTORIAL_MOD


def summarize(results):
    """End-of-run summary line (JSON-ready dict).

    ``rep_accuracy`` is None for configs whose metric came out non-finite
    (empty/degenerate output frame); min() over a None-bearing list
    raises TypeError, which used to crash the sweep AFTER all the work
    was done — filter the Nones and surface how many were dropped.
    """
    accs = [r["rep_accuracy"] for r in results
            if r.get("rep_accuracy") is not None]
    return {
        "configs_run": len(results),
        "min_rep_accuracy": min(accs) if accs else None,
        "configs_without_accuracy": len(results) - len(accs),
    }


def _round_or_none(x, nd=4):
    """NaN-safe metric for the JSON artifact (bare NaN tokens break
    strict RFC 8259 parsers)."""
    x = float(x)
    return None if not np.isfinite(x) else round(x, nd)


def run_config(num_reads, lamb, a, cells_per_clone, num_loci, max_iter,
               seed, mirror_rescue=False, tau_range=None):
    import pandas as pd

    from scdna_replication_tools_tpu.api import scRT

    tut = _tutorial()
    df_s, df_g = tut.make_input_frames(
        num_loci=num_loci, cells_per_clone=cells_per_clone, seed=seed)
    sim_s, sim_g = tut.simulate_pert_frames(
        df_s, df_g, num_reads=num_reads, lamb=lamb, a=a, seed=seed + 1,
        tau_range=tau_range)

    t0 = time.perf_counter()
    scrt = scRT(sim_s, sim_g, cn_prior_method="g1_clones",
                max_iter=max_iter, min_iter=100,
                mirror_rescue=mirror_rescue)
    cn_s_out, supp_s, _, _ = scrt.infer(level="pert")
    wall = time.perf_counter() - t0

    per_cell = cn_s_out.drop_duplicates("cell_id")
    lam_rows = supp_s.query("param == 'model_lambda'")["value"] \
        if "param" in supp_s.columns else pd.Series(dtype=float)
    model_lambda = float(lam_rows.iloc[-1]) if len(lam_rows) else float("nan")
    return {
        "num_reads": num_reads, "lamb": lamb, "a": a,
        "cells_per_clone": cells_per_clone, "num_loci": num_loci,
        "max_iter": max_iter, "seed": seed,
        "tau_range": list(tau_range) if tau_range else None,
        "mirror_rescue": bool(mirror_rescue),
        "mirror_rescue_stats": getattr(scrt, "mirror_rescue_stats", None),
        "rep_accuracy": _round_or_none(
            (cn_s_out.model_rep_state == cn_s_out.true_rep).mean()),
        "cn_accuracy": _round_or_none(
            (cn_s_out.model_cn_state == cn_s_out.true_somatic_cn).mean()),
        "tau_corr": _round_or_none(np.corrcoef(
            per_cell.model_tau, per_cell.true_t)[0, 1]),
        "lambda_abs_err": _round_or_none(abs(model_lambda - lamb)),
        "wall_seconds": round(wall, 1),
    }


def run_genome_mirror_config(num_cells, num_g1, bin_size, max_iter, seed,
                             mirror_rescue):
    """Mirror-stress arm: the genome workload (full_pipeline_bench's
    generative model, mcf7rt RT profile) at reduced scale.

    The tutorial simulator's sin-wave RT profile is informative enough
    that ``guess_times`` never lands in the wrong mirror basin — its
    rescue arm is structurally a no-op twin (ACCURACY_r05_cpu.json:
    every config candidates<=1, accepted=0).  The genome workload's
    flatter empirical RT profile DOES produce wrong-basin boundary fits
    (the r5 A/B pair records 5 candidates / 5 accepted at 100 cells), so
    this config exercises the acceptance path for real.  Metrics are the
    subset the genome truth supports: tau_corr + cn_accuracy (its truth
    frame has no per-bin replication states).
    """
    from full_pipeline_bench import make_genome_workload

    from scdna_replication_tools_tpu.api import scRT

    df_s, df_g, truth_s = make_genome_workload(num_cells, num_g1,
                                               bin_size=bin_size, seed=seed)
    t0 = time.perf_counter()
    scrt = scRT(df_s, df_g, cn_prior_method="g1_clones",
                max_iter=max_iter, min_iter=100,
                mirror_rescue=mirror_rescue)
    cn_s_out, supp_s, _, _ = scrt.infer(level="pert")
    wall = time.perf_counter() - t0

    per_cell = cn_s_out.drop_duplicates("cell_id").set_index("cell_id")
    merged = per_cell.join(truth_s.set_index("cell_id"))
    return {
        "workload": "genome_mirror_stress",
        "num_cells": num_cells, "num_g1": num_g1, "bin_size": bin_size,
        "max_iter": max_iter, "seed": seed,
        "mirror_rescue": bool(mirror_rescue),
        "mirror_rescue_stats": getattr(scrt, "mirror_rescue_stats", None),
        "rep_accuracy": None,   # genome truth has no per-bin rep states
        "cn_accuracy": _round_or_none(
            (cn_s_out.model_cn_state == cn_s_out.state).mean()),
        "tau_corr": _round_or_none(np.corrcoef(
            merged.model_tau, merged.true_t)[0, 1]),
        "wall_seconds": round(wall, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells-per-clone", type=int, default=20)
    ap.add_argument("--loci", type=int, default=150)
    ap.add_argument("--max-iter", type=int, default=400)
    ap.add_argument("--num-reads", type=int, nargs="+",
                    default=[10_000, 25_000, 50_000],
                    help="coverage sweep: reads per cell")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--mirror-rescue", action="store_true",
                    help="also run every coverage with the mirror-basin "
                         "rescue enabled, for a paired comparison")
    ap.add_argument("--mirror-stress", action="store_true",
                    help="append a genome-workload configuration (the "
                         "empirical mcf7rt profile, 64 cells) run with "
                         "rescue off AND on — unlike the tutorial "
                         "simulator's highly informative sin-wave RT "
                         "profile (whose rescue arm is a structural "
                         "no-op twin), this workload actually puts "
                         "guess_times in the wrong mirror basin, so the "
                         "rescue arm records accepted > 0")
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default="ambient",
                    choices=["ambient", "cpu"])
    args = ap.parse_args(argv)
    if args.platform == "cpu":
        from full_pipeline_bench import force_cpu_backend

        force_cpu_backend()

    results = []
    for num_reads in args.num_reads:
        for rescue in ([False, True] if args.mirror_rescue else [False]):
            r = run_config(num_reads, lamb=0.75, a=10.0,
                           cells_per_clone=args.cells_per_clone,
                           num_loci=args.loci, max_iter=args.max_iter,
                           seed=args.seed, mirror_rescue=rescue)
            print(json.dumps(r))
            results.append(r)
    if args.mirror_stress:
        for rescue in (False, True):
            r = run_genome_mirror_config(
                num_cells=64, num_g1=16, bin_size=2_000_000,
                max_iter=args.max_iter, seed=args.seed,
                mirror_rescue=rescue)
            print(json.dumps(r))
            results.append(r)

    import jax

    out = {
        "metric": "pert_simulate_and_recover_accuracy",
        "platform": jax.devices()[0].platform,
        "configs": results,
        "note": "metrics vs the generative truth of models/simulator.py; "
                "the reference validates the same workloads only visually "
                "(notebooks)",
    }
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
    print(json.dumps(summarize(results)))
    return out


if __name__ == "__main__":
    main()
