"""Simulate-and-recover accuracy sweep: PERT vs generative truth.

The reference's only accuracy evidence is visual notebook inspection
(SURVEY.md §4); this tool quantifies recovery on the simulator's own
ground truth across coverage levels — the testing idiom SURVEY
recommends, as a committed artifact.  For each configuration it
simulates a 2-clone chr1 workload (``pert_simulator``), runs the full
``scRT.infer('pert')`` pipeline, and records:

* ``rep_accuracy``   — per-bin replication-state agreement with true_rep
* ``cn_accuracy``    — per-bin CN-state agreement with true_somatic_cn
* ``tau_corr``       — Pearson r of fitted model_tau vs generative true_t
* ``lambda_abs_err`` — |model_lambda − simulated lambda|

Writes one JSON artifact (--out).  CPU-runnable in a few minutes at the
default sizes; the metrics are hardware-independent.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
# sibling tools are importable too (force_cpu_backend lives in
# full_pipeline_bench)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))


_TUTORIAL_MOD = None


def _tutorial():
    """Import examples/tutorial.py (not a package) for its frame builder,
    once — re-executing it per config would stack duplicate sys.path
    entries from its module body."""
    global _TUTORIAL_MOD
    if _TUTORIAL_MOD is None:
        path = (pathlib.Path(__file__).resolve().parents[1]
                / "examples" / "tutorial.py")
        spec = importlib.util.spec_from_file_location("pert_tutorial", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _TUTORIAL_MOD = mod
    return _TUTORIAL_MOD


def summarize(results):
    """End-of-run summary line (JSON-ready dict).

    ``rep_accuracy`` is None for configs whose metric came out non-finite
    (empty/degenerate output frame); min() over a None-bearing list
    raises TypeError, which used to crash the sweep AFTER all the work
    was done — filter the Nones and surface how many were dropped.
    """
    accs = [r["rep_accuracy"] for r in results
            if r.get("rep_accuracy") is not None]
    return {
        "configs_run": len(results),
        "min_rep_accuracy": min(accs) if accs else None,
        "configs_without_accuracy": len(results) - len(accs),
    }


def _round_or_none(x, nd=4):
    """NaN-safe metric for the JSON artifact (bare NaN tokens break
    strict RFC 8259 parsers)."""
    x = float(x)
    return None if not np.isfinite(x) else round(x, nd)


def run_config(num_reads, lamb, a, cells_per_clone, num_loci, max_iter,
               seed, mirror_rescue=False):
    import pandas as pd

    from scdna_replication_tools_tpu.api import scRT

    tut = _tutorial()
    df_s, df_g = tut.make_input_frames(
        num_loci=num_loci, cells_per_clone=cells_per_clone, seed=seed)
    sim_s, sim_g = tut.simulate_pert_frames(
        df_s, df_g, num_reads=num_reads, lamb=lamb, a=a, seed=seed + 1)

    t0 = time.perf_counter()
    scrt = scRT(sim_s, sim_g, cn_prior_method="g1_clones",
                max_iter=max_iter, min_iter=100,
                mirror_rescue=mirror_rescue)
    cn_s_out, supp_s, _, _ = scrt.infer(level="pert")
    wall = time.perf_counter() - t0

    per_cell = cn_s_out.drop_duplicates("cell_id")
    lam_rows = supp_s.query("param == 'model_lambda'")["value"] \
        if "param" in supp_s.columns else pd.Series(dtype=float)
    model_lambda = float(lam_rows.iloc[-1]) if len(lam_rows) else float("nan")
    return {
        "num_reads": num_reads, "lamb": lamb, "a": a,
        "cells_per_clone": cells_per_clone, "num_loci": num_loci,
        "max_iter": max_iter, "seed": seed,
        "mirror_rescue": bool(mirror_rescue),
        "mirror_rescue_stats": getattr(scrt, "mirror_rescue_stats", None),
        "rep_accuracy": _round_or_none(
            (cn_s_out.model_rep_state == cn_s_out.true_rep).mean()),
        "cn_accuracy": _round_or_none(
            (cn_s_out.model_cn_state == cn_s_out.true_somatic_cn).mean()),
        "tau_corr": _round_or_none(np.corrcoef(
            per_cell.model_tau, per_cell.true_t)[0, 1]),
        "lambda_abs_err": _round_or_none(abs(model_lambda - lamb)),
        "wall_seconds": round(wall, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells-per-clone", type=int, default=20)
    ap.add_argument("--loci", type=int, default=150)
    ap.add_argument("--max-iter", type=int, default=400)
    ap.add_argument("--num-reads", type=int, nargs="+",
                    default=[10_000, 25_000, 50_000],
                    help="coverage sweep: reads per cell")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--mirror-rescue", action="store_true",
                    help="also run every coverage with the mirror-basin "
                         "rescue enabled, for a paired comparison")
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default="ambient",
                    choices=["ambient", "cpu"])
    args = ap.parse_args(argv)
    if args.platform == "cpu":
        from full_pipeline_bench import force_cpu_backend

        force_cpu_backend()

    results = []
    for num_reads in args.num_reads:
        for rescue in ([False, True] if args.mirror_rescue else [False]):
            r = run_config(num_reads, lamb=0.75, a=10.0,
                           cells_per_clone=args.cells_per_clone,
                           num_loci=args.loci, max_iter=args.max_iter,
                           seed=args.seed, mirror_rescue=rescue)
            print(json.dumps(r))
            results.append(r)

    import jax

    out = {
        "metric": "pert_simulate_and_recover_accuracy",
        "platform": jax.devices()[0].platform,
        "configs": results,
        "note": "metrics vs the generative truth of models/simulator.py; "
                "the reference validates the same workloads only visually "
                "(notebooks)",
    }
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1)
    print(json.dumps(summarize(results)))
    return out


if __name__ == "__main__":
    main()
