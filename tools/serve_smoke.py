"""Serve smoke: end-to-end proof of the pertserve worker's three core
claims, runnable on CPU in CI (the serve-smoke job) and locally.

One worker session over four queued requests:

1. **r1 (clean)** — the cold request: compiles the bucket's programs
   (compile-cache misses expected);
2. **r2 (faulted)** — carries ``faults='oom@step2/fit#1'``: the
   injected OOM escapes the step-2 fit, the durable-run ladder audits
   ``abort_resumable`` in r2's own RunLog, and the WORKER SURVIVES —
   per-request fault isolation;
3. **r3 (clean, same bucket)** — the warm request: must be a 100%
   program-cache hit (ZERO compile misses in its RunLog) and its
   outputs must be BIT-IDENTICAL to a golden direct ``scRT`` run of
   the same frames under the same bucket padding — a faulted
   neighbour request corrupts nothing;
4. **r4 (mismatched shape)** — larger than the worker's largest
   bucket: refused at admission, never compiled.

Two follow-on sessions ride the now-warm program cache:

5. **batched** — a ``max_batch=2`` worker over three same-bucket
   requests: the first two pack one device slab (the coordinator's
   packed-dispatch counter proves it), the first to converge retires
   early (``retired_early`` on its outcome) and the third request
   REFILLS the vacated block mid-slab; the third request's RunLog
   must be a zero-miss cache hit (the slab program compiled once,
   for the whole session);
6. **shared spool** — two workers drain ONE spool concurrently:
   rename-based claiming means each request lands exactly once;
7. **restart** — a NEW worker (empty in-process program cache) over
   the base session's spool: its first same-bucket request pays ZERO
   XLA compiles, deserializing every program from the persistent
   executable store the first worker left behind.

Writes a JSON verdict (``--out``), copies r3's RunLog to
``<workdir>/warm_request.jsonl`` (the CI fleet-regress step gates its
compile-cache metrics against the committed
``artifacts/FLEET_BASELINE_serve_cpu.json``), and renders r3's
markdown report (``--report``) via tools/pert_report.py.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import shutil
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))


REQUEST_OPTIONS = {
    "max_iter": 150, "min_iter": 50, "run_step3": False,
    "mirror_rescue": False, "seed": 0, "cn_prior_method": "g1_clones",
}
# mirror_rescue off: the rescue sub-fit's program is shaped by the
# CANDIDATE COUNT, which varies per cohort — a warm request with a
# different candidate count would honestly re-compile that one
# program.  The bucket contract covers the batch-shaped programs; the
# smoke pins exactly that (see OBSERVABILITY.md "Serving").


def _frames(num_loci, cells_per_clone, seed):
    from accuracy_sweep import _tutorial

    tut = _tutorial()
    df_s, df_g = tut.make_input_frames(num_loci=num_loci,
                                       cells_per_clone=cells_per_clone,
                                       seed=seed)
    return tut.simulate_pert_frames(df_s, df_g, num_reads=8000,
                                    lamb=0.75, a=10.0, seed=seed + 1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default="serve_smoke")
    ap.add_argument("--out", default=None,
                    help="write the JSON verdict here too")
    ap.add_argument("--report", default=None,
                    help="render r3's run log to markdown here")
    ap.add_argument("--loci", type=int, default=48)
    ap.add_argument("--cells-per-clone", type=int, default=4)
    args = ap.parse_args(argv)

    from scdna_replication_tools_tpu.api import scRT
    from scdna_replication_tools_tpu.obs.schema import validate_run
    from scdna_replication_tools_tpu.obs.summary import summarize_run
    from scdna_replication_tools_tpu.serve import (
        BucketSet,
        ServeWorker,
        SpoolQueue,
    )

    workdir = pathlib.Path(args.workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    queue = SpoolQueue(workdir / "spool")

    # small ladders so the mismatched-shape refusal is cheap to build:
    # the largest bucket holds the smoke cohort, r4 overflows the loci
    # ladder
    buckets = BucketSet(cells=(8, 16, 32), loci=(64, 128))

    sim_a = _frames(args.loci, args.cells_per_clone, seed=3)
    sim_b = _frames(args.loci, args.cells_per_clone, seed=11)
    sim_big = _frames(256, args.cells_per_clone, seed=5)

    r1 = queue.submit_frames(*sim_a, options=REQUEST_OPTIONS,
                             request_id="r1_cold")
    r2 = queue.submit_frames(
        *sim_a, options={**REQUEST_OPTIONS,
                         "faults": "oom@step2/fit#1"},
        request_id="r2_faulted")
    r3 = queue.submit_frames(*sim_b, options=REQUEST_OPTIONS,
                             request_id="r3_warm")
    r4 = queue.submit_frames(*sim_big, options=REQUEST_OPTIONS,
                             request_id="r4_oversized")

    worker = ServeWorker(
        queue, buckets=buckets, max_requests=4, exit_when_idle=True,
        metrics_textfile=str(workdir / "pert_serve.prom"))
    stats = worker.run()

    failures = []

    def check(ok, label):
        (failures.append(label) if not ok else None)
        print(("ok    " if ok else "FAIL  ") + label)

    by_id = {o["request_id"]: o for o in stats["outcomes"]}
    check(stats["processed"] == 4, "worker processed all 4 requests")
    check(by_id.get(r1, {}).get("status") == "ok", "r1 (cold) ok")
    check(by_id.get(r2, {}).get("status") == "failed",
          "r2 (injected oom@step2/fit#1) failed in isolation")
    check(by_id.get(r3, {}).get("status") == "ok",
          "r3 (warm) ok AFTER the faulted request — worker survived")
    check(by_id.get(r4, {}).get("status") == "refused",
          "r4 (oversized) refused at admission")

    cold_cache = by_id.get(r1, {}).get("compile_cache") or {}
    warm_cache = by_id.get(r3, {}).get("compile_cache") or {}
    check((cold_cache.get("cache_misses") or 0) > 0,
          "r1 paid compile misses (cold)")
    check(warm_cache.get("cache_misses") == 0
          and (warm_cache.get("cache_hits") or 0) > 0,
          "r3 is a 100% program-cache hit (zero compile misses)")

    # schema validity: the worker log (request lifecycle events) and
    # the warm request's own log
    worker_errors = validate_run(stats["worker_log"])
    check(worker_errors == [], "worker RunLog is schema-valid (v7)")
    r3_log = by_id.get(r3, {}).get("run_log")
    r3_errors = validate_run(r3_log) if r3_log else ["missing"]
    check(r3_errors == [], "r3 RunLog is schema-valid")

    # r2's own artifacts carry the fault audit
    r2_summary = summarize_run(by_id.get(r2, {}).get("run_log")) or {}
    resil = r2_summary.get("resilience") or {}
    check(any(f.get("kind") == "oom" for f in resil.get("faults", [])),
          "r2 RunLog audits the injected oom fault")

    # golden parity: direct scRT on r3's frames under the SAME bucket
    # padding — the warm serve path must be bit-identical to it
    bucket = by_id.get(r3, {}).get("bucket") or {}
    scrt = scRT(sim_b[0].copy(), sim_b[1].copy(),
                telemetry_path=str(workdir / "golden.jsonl"),
                pad_cells_to=bucket.get("cells"),
                pad_loci_to=bucket.get("loci"),
                **REQUEST_OPTIONS)
    golden_out, _, _, _ = scrt.infer(level="pert")

    import pandas as pd

    served = pd.read_csv(
        queue.results_dir(r3) / "output.tsv", sep="\t",
        dtype={"chr": str})
    g = golden_out.sort_values(["cell_id", "chr", "start"]) \
        .reset_index(drop=True)
    s = served.sort_values(["cell_id", "chr", "start"]) \
        .reset_index(drop=True)
    check(len(g) == len(s) and len(s) > 0,
          "served output covers the golden rows")
    import numpy as np

    # compare at the output's native float32 precision: the served
    # side round-trips through a TSV (shortest-repr float text), which
    # is exact at float32 but not against the float64 the reader
    # parses into
    tau_equal = bool((g["model_tau"].to_numpy(np.float32)
                      == s["model_tau"].to_numpy(np.float32)).all())
    cn_equal = bool((g["model_cn_state"].to_numpy()
                     == s["model_cn_state"].to_numpy()).all())
    check(tau_equal, "r3 model_tau bit-identical to the golden run")
    check(cn_equal, "r3 model_cn_state identical to the golden run")

    check((queue.results_dir(r3) / "cell_qc.tsv").exists(),
          "r3 per-request cell_qc table streamed back")

    # -- batched session: slab packing, early retirement, refill ----------
    # three same-bucket requests through a max_batch=2 worker: b1+b2
    # pack one slab, the first to converge retires early, b3 joins by
    # refilling the vacated block.  Rides the warm solo ledger from
    # the base session; the W=2 slab program compiles ONCE here, so
    # b3 (admitted after that compile) must still be a zero-miss hit.
    bq = SpoolQueue(workdir / "spool_batched")
    b1 = bq.submit_frames(*sim_a, options=REQUEST_OPTIONS,
                          request_id="b1_slab")
    b2 = bq.submit_frames(*sim_b, options=REQUEST_OPTIONS,
                          request_id="b2_slab")
    b3 = bq.submit_frames(*sim_a, options=REQUEST_OPTIONS,
                          request_id="b3_refill")
    bworker = ServeWorker(bq, buckets=buckets, max_requests=3,
                          exit_when_idle=True, max_batch=2)
    bstats = bworker.run()
    b_by_id = {o["request_id"]: o for o in bstats["outcomes"]}
    check(all(b_by_id.get(r, {}).get("status") == "ok"
              for r in (b1, b2, b3)),
          "batched: all three slab requests ok")
    coord = bworker.slab_coordinator
    check(coord is not None and coord.packed_dispatches > 0,
          "batched: the coordinator packed fits into slab dispatches")
    check(any(o.get("retired_early") for o in bstats["outcomes"]),
          "batched: a converged block retired early (peers kept "
          "fitting)")
    # the refilled request rides the session's warm ledgers: its own
    # RunLog must not recompile any request-level program.  A slab-
    # tagged miss is tolerated — whichever thread happens to LEAD the
    # first packed dispatch of a step carries that one-time compile in
    # its ledger (compile events carry `tag`; `slab<W>` marks the
    # W-wide batched program rung)
    b3_cache = b_by_id.get(b3, {}).get("compile_cache") or {}
    b3_log = b_by_id.get(b3, {}).get("run_log")
    b3_nonslab_misses = []
    if b3_log:
        with open(b3_log) as fh:
            for line in fh:
                ev = json.loads(line)
                if (ev.get("event") == "compile"
                        and ev.get("cache") == "miss"
                        and not str(ev.get("tag", "")
                                    ).startswith("slab")):
                    b3_nonslab_misses.append(ev.get("tag"))
    check(b3_log is not None and not b3_nonslab_misses,
          "batched: the refilled request recompiles nothing but (at "
          f"most) the shared slab program (non-slab misses: "
          f"{b3_nonslab_misses})")
    check(validate_run(bstats["worker_log"]) == [],
          "batched: worker RunLog is schema-valid")

    # -- shared spool: two workers, one queue -----------------------------
    import threading

    sq = SpoolQueue(workdir / "spool_shared")
    s1 = sq.submit_frames(*sim_a, options=REQUEST_OPTIONS,
                          request_id="s1_shared")
    s2 = sq.submit_frames(*sim_b, options=REQUEST_OPTIONS,
                          request_id="s2_shared")
    sworkers = [ServeWorker(sq, buckets=buckets, max_requests=1,
                            exit_when_idle=True) for _ in range(2)]
    sstats = [None, None]

    def _drain(i):
        sstats[i] = sworkers[i].run()

    sthreads = [threading.Thread(target=_drain, args=(i,))
                for i in range(2)]
    for t in sthreads:
        t.start()
    for t in sthreads:
        t.join(timeout=600)
    shared_ok = (sstats[0] is not None and sstats[1] is not None)
    served_ids = []
    if shared_ok:
        for st in sstats:
            served_ids += [o["request_id"] for o in st["outcomes"]]
    check(shared_ok and sorted(served_ids) == sorted([s1, s2]),
          "shared spool: two workers drained one queue, each request "
          "claimed exactly once")
    check(shared_ok and all(
        o["status"] == "ok" for st in sstats for o in st["outcomes"]),
        "shared spool: both requests ok")

    # -- restart: a NEW worker on the pre-warmed spool ---------------------
    # the base session's worker persisted every compiled executable
    # into <spool>/exec_cache (the "auto" default).  A restarted worker
    # has an empty in-process program cache — simulated here by
    # clearing it and deactivating the store binding — but its first
    # same-bucket request must pay ZERO XLA compiles: every program
    # resolution deserializes from the disk store (cache="disk_hit").
    from scdna_replication_tools_tpu.infer import aotcache as _aotcache
    from scdna_replication_tools_tpu.infer import svi as _svi

    _svi.clear_program_cache()
    _aotcache.deactivate()
    rr = queue.submit_frames(*sim_a, options=REQUEST_OPTIONS,
                             request_id="rr_restart")
    rworker = ServeWorker(queue, buckets=buckets, max_requests=1,
                          exit_when_idle=True)
    rstats = rworker.run()
    r_by_id = {o["request_id"]: o for o in rstats["outcomes"]}
    check(r_by_id.get(rr, {}).get("status") == "ok",
          "restart: first request on the restarted worker ok")
    rr_cache = r_by_id.get(rr, {}).get("compile_cache") or {}
    check(rr_cache.get("cache_misses") == 0
          and (rr_cache.get("disk_hits") or 0) > 0,
          "restart: zero XLA compiles — every program deserialized "
          f"from the executable store (ledger: {rr_cache})")

    # stable copy of the warm request's log for the CI fleet gate
    if r3_log:
        shutil.copy(r3_log, workdir / "warm_request.jsonl")

    if args.report and r3_log:
        from pert_report import render_report

        pathlib.Path(args.report).write_text(render_report(r3_log))

    verdict = {
        "metric": "pert_serve_smoke",
        "ok": not failures,
        "failures": failures,
        "stats": {k: v for k, v in stats.items() if k != "outcomes"},
        "outcomes": stats["outcomes"],
        "cold_compile_cache": cold_cache,
        "warm_compile_cache": warm_cache,
        "warm_request_log": str(workdir / "warm_request.jsonl"),
        "parity": {"tau_bit_identical": tau_equal,
                   "cn_identical": cn_equal},
        "batched": {
            "by_status": bstats["by_status"],
            "packed_dispatches": getattr(coord, "packed_dispatches",
                                         0),
            "packed_lanes": getattr(coord, "packed_lanes", 0),
            "retired_early": sum(
                1 for o in bstats["outcomes"]
                if o.get("retired_early")),
            "refill_compile_cache": b3_cache,
        },
        "shared_spool": {"served": sorted(served_ids)},
        "restart": {"compile_cache": rr_cache},
    }
    print(json.dumps(verdict))
    if args.out:
        pathlib.Path(args.out).write_text(
            json.dumps(verdict, indent=1) + "\n")
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
