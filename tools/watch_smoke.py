"""Watch smoke: the pert-watch run-health loop, end to end, twice.

The CI face of the live run-health plane (obs/heartbeat.py +
tools/pert_watch.py): two arms over the same 2-process
``jax.distributed`` CPU workload (gloo collectives, one forced host
device per process), each process publishing ``health/host_<rank>.json``
heartbeats at a sub-second cadence:

1. **healthy** — both hosts fit to completion.  While they run the
   parent must see BOTH heartbeats live (the mission-control view
   works mid-fit); afterwards both documents must be terminal
   (``state: done`` — "final", exempt from staleness) and
   ``pert_watch check`` must exit 0 with the three watch gauges in its
   Prometheus textfile;
2. **chaos** — same workload with ``preempt@step2/chunk#2@proc1``.
   Host 1 dies by ``SimulatedPreemption`` (a BaseException — the
   heartbeat's terminal write deliberately does NOT run, leaving the
   last document in ``state: running``).  The parent polls the health
   dir and must observe host 1 reach **presumed_lost** purely by
   staleness WHILE host 0 is still alive in its doomed collective —
   the pre-deadlock hostloss flag this plane exists for.  Afterwards
   ``pert_watch check`` must exit non-zero naming
   ``host-presumed-lost``.

Emits one JSON verdict (``--out``) with a checks dict and exits 1 when
any check fails, same shape as ``tools/chaos_smoke.py``.

Usage::

    python tools/watch_smoke.py --out watch_smoke.json
    python tools/watch_smoke.py --arm chaos --report watch_health.md
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tools.chaos_smoke import _free_port, _infer  # noqa: E402
from tools.full_pipeline_bench import (  # noqa: E402
    force_cpu_backend,
    make_genome_workload,
)

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def _mp_worker(args) -> int:
    """One host of a 2-process fit with heartbeats on (spawned by the
    parent; env already forces one host CPU device per process).

    Exit codes: 0 = finished, 3 = died by the injected preemption,
    4 = died collaterally (peer gone, collective failed)."""
    from scdna_replication_tools_tpu.parallel.distributed import (
        init_distributed,
    )
    from scdna_replication_tools_tpu.utils import faults as faults_mod

    init_distributed(coordinator_address=args.coordinator,
                     num_processes=2, process_id=args.mp_worker)
    work = pathlib.Path(args.workdir)
    df_s, df_g, _ = make_genome_workload(args.cells, args.g1_cells,
                                         bin_size=args.bin_size, seed=0)
    extra = {
        "heartbeat_dir": str(work / "health"),
        "heartbeat_interval_seconds": args.hb_interval,
        "num_shards": 2, "elastic_mesh": False,
        "watchdog_chunk_seconds": 60.0,
    }
    if args.faults:
        extra["faults"] = args.faults
    try:
        _infer(df_s, df_g,
               str(work / f"run.p{args.mp_worker}.jsonl"), **extra)
    except faults_mod.SimulatedPreemption as exc:
        print(f"watch-smoke worker {args.mp_worker}: preempted ({exc})",
              file=sys.stderr)
        return 3
    except RuntimeError as exc:
        # the post-fit dataframe decode fetches global arrays, which a
        # multi-host run cannot do yet (the ROADMAP-1 decode gap; the
        # mirror rescue is gated the same way).  The FIT completed iff
        # this process's own heartbeat closed terminal "done" — which
        # is exactly the ground truth this smoke exists to establish.
        from scdna_replication_tools_tpu.obs import heartbeat as hb_mod

        doc = hb_mod.read_heartbeat(
            hb_mod.host_path(work / "health", args.mp_worker)) or {}
        if doc.get("state") == "done" and "non-addressable" in str(exc):
            print(f"watch-smoke worker {args.mp_worker}: fit done "
                  "(multi-host output decode skipped)", file=sys.stderr)
            return 0
        print(f"watch-smoke worker {args.mp_worker}: died collaterally "
              f"({type(exc).__name__}: {exc})", file=sys.stderr)
        return 4
    except BaseException as exc:  # noqa: BLE001 — the worker's whole
        # job is to report HOW it died to the parent
        print(f"watch-smoke worker {args.mp_worker}: died collaterally "
              f"({type(exc).__name__}: {exc})", file=sys.stderr)
        return 4
    return 0


def _spawn_pair(args, work: pathlib.Path, faults: str | None):
    port = _free_port()
    procs = []
    for k in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=1"
                            ).strip()
        env.pop("PERT_FAULTS", None)
        cmd = [sys.executable, __file__, "--mp-worker", str(k),
               "--coordinator", f"127.0.0.1:{port}",
               "--workdir", str(work), "--cells", str(args.cells),
               "--g1-cells", str(args.g1_cells),
               "--bin-size", str(args.bin_size),
               "--hb-interval", str(args.hb_interval)]
        if faults:
            cmd += ["--faults", faults]
        procs.append(subprocess.Popen(cmd, env=env, cwd=str(_REPO_ROOT)))
    return procs


def _wait_all(procs, timeout: float) -> list:
    codes = []
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            codes.append(p.wait(timeout=max(deadline - time.monotonic(),
                                            1.0)))
        except subprocess.TimeoutExpired:
            p.kill()
            codes.append(p.wait())
            print("watch_smoke: killed a hung worker (timeout)",
                  file=sys.stderr)
    return codes


def _run_check(health_dir, textfile=None):
    """``pert_watch check`` as CI runs it — a real subprocess, so the
    exit-code contract is what's exercised."""
    cmd = [sys.executable, str(_REPO_ROOT / "tools" / "pert_watch.py"),
           "check", str(health_dir)]
    if textfile:
        cmd += ["--metrics-textfile", str(textfile)]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         cwd=str(_REPO_ROOT))
    try:
        doc = json.loads(res.stdout)
    except ValueError:
        doc = {}
    return res.returncode, doc, res.stderr


def _watch_frame(health_dir) -> str:
    from scdna_replication_tools_tpu.obs import alerts as alerts_mod
    from scdna_replication_tools_tpu.obs import heartbeat as hb_mod
    from tools.pert_watch import render_view

    agg = hb_mod.aggregate_health(health_dir)
    verdicts = alerts_mod.evaluate(alerts_mod.load_rules(), agg)
    return render_view(health_dir, agg, verdicts)


def _healthy_arm(args, work: pathlib.Path) -> dict:
    from scdna_replication_tools_tpu.obs import heartbeat as hb_mod

    health = work / "health"
    procs = _spawn_pair(args, work, faults=None)
    # live visibility: both heartbeats must appear while the fit runs
    saw_both_live = False
    while any(p.poll() is None for p in procs):
        agg = hb_mod.aggregate_health(health)
        if agg["hosts_seen"] >= 2:
            saw_both_live = True
            break
        time.sleep(0.5)
    codes = _wait_all(procs, timeout=600)
    print(_watch_frame(health), file=sys.stderr)
    states = {r["rank"]: r["doc"].get("state")
              for r in hb_mod.scan_health(health)}
    prom = work / "watch.prom"
    rc, doc, err = _run_check(health, textfile=prom)
    text = prom.read_text() if prom.exists() else ""
    return {
        "exit_codes": codes,
        "checks": {
            "healthy_workers_finished_clean": codes == [0, 0],
            "healthy_live_saw_both_hosts": saw_both_live,
            "healthy_both_hosts_done": states == {0: "done", 1: "done"},
            "healthy_check_green": rc == 0
            and doc.get("failing") == [],
            "healthy_textfile_has_watch_gauges": all(
                name in text for name in (
                    "pert_heartbeat_lag_seconds",
                    "pert_straggler_spread_chunks",
                    "pert_run_eta_seconds")),
        },
    }


def _chaos_arm(args, work: pathlib.Path) -> dict:
    from scdna_replication_tools_tpu.obs import heartbeat as hb_mod

    health = work / "health"
    procs = _spawn_pair(args, work,
                        faults=f"preempt@{args.kill_at}@proc1")
    # poll for the hostloss flag: host 1's heartbeat must age through
    # the ladder to presumed_lost while host 0 still lives in its
    # doomed collective (detection BEFORE the run is over)
    detected = False
    survivor_alive_at_detection = False
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        rows = {h["rank"]: h
                for h in hb_mod.aggregate_health(health)["hosts"]}
        lost = rows.get(1)
        if lost is not None and lost["freshness"] == "presumed_lost":
            detected = True
            survivor_alive_at_detection = procs[0].poll() is None
            break
        if all(p.poll() is not None for p in procs) \
                and lost is not None \
                and lost["doc"].get("state") in hb_mod.TERMINAL_STATES:
            break  # scenario bug: the preempted rank wrote a terminal doc
        time.sleep(0.5)
    frame = _watch_frame(health)
    print(frame, file=sys.stderr)
    codes = _wait_all(procs, timeout=600)
    host1 = hb_mod.read_heartbeat(hb_mod.host_path(health, 1)) or {}
    rc, doc, err = _run_check(health)
    return {
        "exit_codes": codes,
        "check_stderr": err.strip(),
        "checks": {
            "chaos_proc1_died_by_preemption": codes[1] == 3,
            "chaos_lost_host_left_running_state":
                host1.get("state") == "running",
            "chaos_presumed_lost_detected": detected,
            "chaos_detected_before_run_exit":
                survivor_alive_at_detection,
            "chaos_watch_frame_flags_lost": "PRESUMED-LOST" in frame,
            "chaos_check_fails_naming_staleness": rc != 0
            and "host-presumed-lost" in (doc.get("failing") or []),
        },
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=32)
    ap.add_argument("--g1-cells", type=int, default=16)
    ap.add_argument("--bin-size", type=int, default=5_000_000,
                    help="smoke default: a coarse ~620-bin genome keeps "
                         "both arms CI-cheap")
    ap.add_argument("--hb-interval", type=float, default=0.25,
                    help="heartbeat cadence for the workers; the "
                         "presumed-lost threshold is 30x this, so it "
                         "must be small enough to trip while the "
                         "survivor's watchdog (60s) still has it alive")
    ap.add_argument("--kill-at", default="step2/chunk#2",
                    help="fault site of the chaos arm's preemption")
    ap.add_argument("--arm", choices=("healthy", "chaos", "both"),
                    default="both")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--out", default=None, help="JSON verdict path")
    ap.add_argument("--report", default=None,
                    help="write the final 'Run health' markdown of the "
                         "last arm here (the CI artifact)")
    ap.add_argument("--mp-worker", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--coordinator", default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--faults", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.mp_worker is not None:
        return _mp_worker(args)

    force_cpu_backend()

    root = pathlib.Path(args.workdir) if args.workdir \
        else pathlib.Path(tempfile.mkdtemp(prefix="pert_watch_"))
    root.mkdir(parents=True, exist_ok=True)

    checks = {}
    facts = {}
    last_health = None
    if args.arm in ("healthy", "both"):
        print("watch_smoke: healthy arm (2-process fit, heartbeats "
              f"every {args.hb_interval}s)...", file=sys.stderr)
        work = root / "healthy"
        work.mkdir(exist_ok=True)
        facts["healthy"] = _healthy_arm(args, work)
        checks.update(facts["healthy"].pop("checks"))
        last_health = work / "health"
    if args.arm in ("chaos", "both"):
        print("watch_smoke: chaos arm "
              f"(preempt@{args.kill_at}@proc1)...", file=sys.stderr)
        work = root / "chaos"
        work.mkdir(exist_ok=True)
        facts["chaos"] = _chaos_arm(args, work)
        checks.update(facts["chaos"].pop("checks"))
        last_health = work / "health"

    if args.report and last_health is not None:
        from scdna_replication_tools_tpu.obs import alerts as alerts_mod
        from scdna_replication_tools_tpu.obs import heartbeat as hb_mod
        from tools.pert_watch import render_health_markdown

        agg = hb_mod.aggregate_health(last_health)
        verdicts = alerts_mod.evaluate(alerts_mod.load_rules(), agg)
        pathlib.Path(args.report).write_text(
            "\n".join(render_health_markdown(agg, verdicts)) + "\n")

    verdict = {
        "metric": "watch_smoke_run_health_loop",
        "arm": args.arm,
        "hb_interval_seconds": args.hb_interval,
        "kill_at": args.kill_at,
        "checks": checks,
        "facts": facts,
        "ok": all(checks.values()),
        "workdir": str(root),
    }
    print(json.dumps(verdict))
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(verdict, indent=1)
                                          + "\n")
    if not verdict["ok"]:
        failing = [k for k, v in checks.items() if not v]
        print(f"watch_smoke: FAILED checks: {failing}", file=sys.stderr)
        return 1
    print("watch_smoke: OK — run-health loop holds on both arms",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
