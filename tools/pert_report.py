"""Render a RunLog JSONL into a markdown run report; diff two runs.

The regression tool for BENCH/ACCURACY rounds: every telemetry-enabled
run (``PertConfig.telemetry_path``, default 'auto') leaves one JSONL
artifact, and this tool turns it into the tables a perf or
model-health investigation starts from — phase waterfall, per-step fit
table, model health (convergence-doctor verdicts, flagged-cell QC,
entropy histogram), compile-cache hit rate, memory high-water, rescue
summary:

    python tools/pert_report.py RUN.jsonl [--out report.md]
    python tools/pert_report.py --compare COLD.jsonl WARM.jsonl

``--compare`` aligns two runs phase by phase and fit by fit (the
cold/warm compile-cache pair, a before/after of an optimisation, two
BENCH rounds) and reports deltas — a diffable artifact instead of two
terminal scrolls.  Event reference: OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from scdna_replication_tools_tpu.obs.metrics import (  # noqa: E402
    manifest_metrics,
    metric_base_name,
    regress_verdict,
)
from scdna_replication_tools_tpu.obs.summary import (  # noqa: E402
    flat_metrics,
    summarize_run,
)

_BAR_WIDTH = 30


def _fmt_seconds(v) -> str:
    return "-" if v is None else f"{v:.2f}s"


def _fmt_bytes(v) -> str:
    if v is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024 or unit == "GiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024
    return f"{v:.1f} GiB"


def _header(summary: dict) -> list:
    lines = [f"# PERT run report — `{pathlib.Path(summary['path']).name}`",
             ""]
    status = summary.get("status")
    badge = {"ok": "OK", "error": "ERROR", "incomplete": "INCOMPLETE "
             "(no run_end — killed run?)"}.get(status, status)
    lines.append(f"- **status**: {badge}")
    if summary.get("error"):
        err = summary["error"]
        lines.append(f"- **error**: `{err.get('type')}`: "
                     f"{err.get('message')}")
    if summary.get("wall_seconds") is not None:
        lines.append(f"- **wall**: {summary['wall_seconds']:.2f}s "
                     f"(phases account for {summary['phase_total']:.2f}s)")
    plat = summary.get("platform")
    if plat:
        lines.append(f"- **device**: {summary.get('num_devices')}x "
                     f"{summary.get('device_kind')} ({plat}), "
                     f"jax {summary.get('jax_version')}")
    if summary.get("config_hash"):
        lines.append(f"- **config hash**: `{summary['config_hash']}`")
    lines.append(f"- **events**: {summary.get('num_events')}")
    lines.append("")
    return lines


def _phase_waterfall(phases: dict) -> list:
    if not phases:
        return ["## Phase waterfall", "", "_no phase events_", ""]
    total = sum(phases.values()) or 1.0
    lines = ["## Phase waterfall", "",
             "| phase | seconds | share | |",
             "|---|---:|---:|---|"]
    for name, secs in sorted(phases.items(), key=lambda kv: -kv[1]):
        share = secs / total
        bar = "#" * round(share * _BAR_WIDTH)
        lines.append(f"| `{name}` | {secs:.2f} | {share:.1%} | `{bar}` |")
    lines.append(f"| **total accounted** | **{total:.2f}** | | |")
    lines.append("")
    return lines


def _fit_table(fits: list) -> list:
    lines = ["## SVI fits", ""]
    if not fits:
        return lines + ["_no fit_end events_", ""]
    lines += ["| step | iters | final loss | converged | nan | wall | "
              "iters/s | program cache | grad-norm (sampled window) |",
              "|---|---:|---:|---|---|---:|---:|---|---|"]
    for fit in fits:
        diag = fit.get("diagnostics") or {}
        gn = "-"
        if diag.get("samples"):
            # the ring buffer keeps a trailing window; label each value
            # with its iteration so a wrapped ring cannot be misread as
            # the fit's first/overall gradient norms
            lo = diag.get("window_start_iter")
            hi = diag.get("window_end_iter")
            at = (lambda i: f"@i{i}" if i is not None else "")
            # norms are null in the JSONL when non-finite (RFC 8259 has
            # no NaN) — exactly the diverged fits this table post-mortems
            num = (lambda k: "nan" if diag.get(k) is None
                   else f"{diag[k]:.3g}")
            gn = (f"{num('grad_norm_first')}{at(lo)} → "
                  f"{num('grad_norm_last')}{at(hi)} "
                  f"(win max {num('grad_norm_max')})")
        loss = fit.get("final_loss")
        # .get defaults don't fire for keys PRESENT with value None
        # (summary.py materializes optional fields that way)
        opt = (lambda k: "-" if fit.get(k) is None else fit[k])
        lines.append(
            f"| {fit.get('step')} | {fit.get('iters')} "
            f"| {'-' if loss is None else f'{loss:.6g}'} "
            f"| {fit.get('converged')} | {fit.get('nan_abort')} "
            f"| {_fmt_seconds(fit.get('wall_seconds'))} "
            f"| {opt('iters_per_second')} "
            f"| {opt('program_cache')} | {gn} |")
    lines.append("")
    return lines


def _compile_section(comp: dict) -> list:
    lines = ["## Compiled programs", ""]
    if not comp.get("programs"):
        return lines + ["_no compile events_", ""]
    hit_rate = comp.get("hit_rate")
    lines += [
        f"- **programs resolved**: {comp['programs']} "
        f"({comp['cache_hits']} hits / {comp['cache_misses']} misses"
        + (f", hit rate {hit_rate:.0%}" if hit_rate is not None else "")
        + ")",
        f"- **trace**: {comp['trace_seconds']:.2f}s, "
        f"**compile**: {comp['compile_seconds']:.2f}s",
        f"- **memory high-water (largest program)**: "
        f"{_fmt_bytes(comp.get('peak_bytes_max'))}",
        "",
    ]
    return lines


def _model_health_section(fit_health: list, cell_qc: list) -> list:
    """Convergence-doctor verdicts + per-cell QC aggregates (schema v2
    ``fit_health`` / ``cell_qc_summary`` events)."""
    lines = ["## Model health", ""]
    if not fit_health and not cell_qc:
        return lines + ["_no model-health events (QC disabled or a "
                        "pre-v2 run log)_", ""]
    if fit_health:
        lines += ["| step | verdict | drift | rel var | grad decay | "
                  "reason |",
                  "|---|---|---:|---:|---:|---|"]
        num = (lambda v: "-" if v is None else f"{v:.3g}")
        for ev in fit_health:
            verdict = ev.get("verdict") or "?"
            mark = "" if verdict == "converged" else " ⚠"
            lines.append(
                f"| {ev.get('step')} | **{verdict}**{mark} "
                f"| {num(ev.get('drift'))} | {num(ev.get('rel_var'))} "
                f"| {num(ev.get('grad_decay'))} "
                f"| {ev.get('reason') or '-'} |")
        lines.append("")
    for ev in cell_qc:
        n = ev.get("num_cells") or 0
        flagged = ev.get("num_flagged") or 0
        pct = f" ({flagged / n:.1%})" if n else ""
        counts = ev.get("flag_counts") or {}
        detail = ", ".join(f"{k}: {v}" for k, v in counts.items())
        lines.append(f"- **cell QC ({ev.get('step')})**: {n} cells, "
                     f"{flagged} flagged{pct}"
                     + (f" — {detail}" if detail else ""))
        if ev.get("mean_cn_entropy_mean") is not None:
            lines.append(f"- **mean CN-posterior entropy**: "
                         f"{ev['mean_cn_entropy_mean']:.4f}"
                         + (f", max PPC z: {ev['ppc_z_max']:.2f}"
                            if ev.get("ppc_z_max") is not None else ""))
        hist = ev.get("entropy_hist") or []
        if hist and max(hist):
            lines += ["", "  per-cell mean CN entropy histogram "
                          "(bins of 0.1 over [0, 1]):", "  ```"]
            peak = max(hist)
            for i, count in enumerate(hist):
                bar = "#" * round(count / peak * _BAR_WIDTH)
                lines.append(f"  {i / 10:.1f}-{(i + 1) / 10:.1f} "
                             f"{bar} {count}")
            lines.append("  ```")
        flagged_cells = ev.get("flagged_cells") or []
        if flagged_cells:
            lines += ["", "| flagged cell | reasons | tau | frac "
                          "low-conf | PPC z |",
                      "|---|---|---:|---:|---:|"]
            num = (lambda v, fmt="{:.3f}": "-" if v is None
                   else fmt.format(v))
            for cell in flagged_cells[:10]:
                lines.append(
                    f"| `{cell.get('cell_id')}` "
                    f"| {', '.join(cell.get('reasons') or [])} "
                    f"| {num(cell.get('tau'))} "
                    f"| {num(cell.get('frac_low_conf'))} "
                    f"| {num(cell.get('ppc_z'), '{:.2f}')} |")
            if len(flagged_cells) > 10:
                lines.append(f"| _… {len(flagged_cells) - 10} more in "
                             f"the event_ | | | | |")
        lines.append("")
    return lines


def _decision_trail_section(control: list, agg: dict) -> list:
    """The adaptive fit controller's audit trail (schema v3
    ``control_decision`` events): what the controller saw, what it did,
    and the iteration ledger — the section that makes adaptive fits
    reproducible from the artifact alone."""
    lines = ["## Decision trail", ""]
    if not control:
        return lines + ["_no control_decision events (controller off, "
                        "inert, or a pre-v3 run log)_", ""]
    saved = agg.get("iters_saved", 0)
    granted = agg.get("iters_granted", 0)
    actions = agg.get("actions") or {}
    lines += [
        f"- **decisions**: {len(control)} ("
        + ", ".join(f"{k}: {v}" for k, v in actions.items()) + ")",
        f"- **iterations reclaimed (early stops)**: {saved}",
        f"- **iterations granted (extensions)**: {granted}",
        "",
        "| step | iter | action | verdict | drift | rel var | "
        "grad decay | saved/granted | detail |",
        "|---|---:|---|---|---:|---:|---:|---:|---|",
    ]
    num = (lambda v: "-" if v is None else f"{v:.3g}")
    for d in control:
        trig = d.get("trigger") or {}
        ledger = "-"
        if d.get("iters_saved") is not None:
            ledger = f"-{d['iters_saved']}"
        elif d.get("iters_granted") is not None:
            ledger = f"+{d['iters_granted']}"
        detail = d.get("detail") or d.get("outcome") or ""
        reason = trig.get("reason") or ""
        lines.append(
            f"| {d.get('step')} | {d.get('iter')} "
            f"| **{d.get('action')}** "
            f"| {trig.get('verdict') or '-'} "
            f"| {num(trig.get('drift'))} | {num(trig.get('rel_var'))} "
            f"| {num(trig.get('grad_decay'))} | {ledger} "
            f"| {detail or reason} |")
    lines.append("")
    return lines


def _resilience_section(res: dict, schema_version) -> list:
    """The durability trail (schema v4 ``fault_injected`` / ``retry`` /
    ``degrade`` / ``resume`` events + checkpoint traffic): what went
    wrong, what the recovery ladder did about it, and how the run's
    state survived — the audit a chaos test or a post-mortem reads
    first.  Placeholder on pre-v4 logs."""
    lines = ["## Resilience", ""]
    res = res or {}
    events = (res.get("faults") or []) + (res.get("retries") or []) \
        + (res.get("degrades") or []) + (res.get("resumes") or [])
    if not events and not res.get("checkpoint_saves"):
        if schema_version is not None and schema_version < 4:
            return lines + ["_pre-v4 run log: no durability events in "
                            "this schema version_", ""]
        return lines + ["_clean run: no faults injected, no retries, "
                        "no degradations, no resumes_", ""]
    lines.append(f"- **checkpoints**: {res.get('checkpoint_saves', 0)} "
                 f"saved, {res.get('checkpoint_loads', 0)} loaded")
    for ev in res.get("resumes") or []:
        verified = ("fingerprint verified"
                    if ev.get("fingerprint_verified")
                    else "fingerprint NOT verified")
        frm = (f" from iteration {ev['from_iter']}"
               if ev.get("from_iter") is not None else "")
        lines.append(f"- **resume ({ev.get('step')})**: "
                     f"{ev.get('action')}{frm} (mode "
                     f"{ev.get('mode')}, {verified})")
    for ev in res.get("faults") or []:
        lines.append(f"- **fault injected**: `{ev.get('kind')}` at "
                     f"`{ev.get('site')}` (hit {ev.get('hit')})")
    for ev in res.get("retries") or []:
        lines.append(f"- **retry**: `{ev.get('label')}` attempt "
                     f"{ev.get('attempt')}/{ev.get('max_attempts')} "
                     f"after {ev.get('delay_seconds')}s — "
                     f"{ev.get('error') or ev.get('error_class')}")
    for ev in res.get("degrades") or []:
        lines.append(f"- **degrade ({ev.get('step') or '-'})**: "
                     f"`{ev.get('action')}` — {ev.get('detail') or ''}")
    lines.append("")
    return lines


def _spans_section(summary: dict) -> list:
    """"Where the time went" (schema v8 ``span_end`` events): the span
    rollup as a component waterfall — queue-wait / admission / pad /
    compile / fit / decode / stream-back — plus the raw per-name
    table, and a per-request latency table on serve worker logs.
    Placeholder on tracing-off / pre-v8 logs."""
    lines = ["## Where the time went (spans)", ""]
    spans = summary.get("spans") or {}
    by_name = spans.get("by_name") or {}
    if not by_name:
        version = summary.get("schema_version")
        if version is not None and version < 8:
            return lines + ["_pre-v8 run log: no span events in this "
                            "schema version_", ""]
        return lines + ["_no span_end events (tracing off — enable "
                        "with --trace-spans / PertConfig.trace_spans; "
                        "the serve worker traces by default)_", ""]
    from tools.pert_trace import WATERFALL_COMPONENTS, classify_span

    components = {c: 0.0 for c in WATERFALL_COMPONENTS}
    for name, slot in by_name.items():
        comp = classify_span(name)
        if comp is not None:
            components[comp] += float(slot.get("seconds") or 0.0)
    total = sum(components.values()) or 1.0
    lines += ["| component | seconds | share | |",
              "|---|---:|---:|---|"]
    for comp in WATERFALL_COMPONENTS:
        secs = components[comp]
        if secs == 0.0:
            continue
        share = secs / total
        bar = "#" * round(share * _BAR_WIDTH)
        lines.append(f"| {comp} | {secs:.2f} | {share:.1%} | `{bar}` |")
    lines.append(f"| **total (leaf spans)** | **{total:.2f}** | | |")
    lines += ["", "| span | count | seconds |", "|---|---:|---:|"]
    for name, slot in sorted(by_name.items(),
                             key=lambda kv: -kv[1]["seconds"]):
        lines.append(f"| `{name}` | {slot['count']} "
                     f"| {slot['seconds']:.2f} |")
    requests = summary.get("requests") or []
    if requests:
        lines += ["", "per-request latency (serve mode; pad/compile/"
                      "fit/decode live in each request's own run log — "
                      "`python -m tools.pert_trace waterfall`):", "",
                  "| request | status | queue wait | wall |",
                  "|---|---|---:|---:|"]
        for req in requests:
            qw = req.get("queue_wait_seconds")
            lines.append(
                f"| {req.get('request_id')} | {req.get('status')} "
                f"| {'-' if qw is None else f'{qw:.2f}s'} "
                f"| {_fmt_seconds(req.get('wall_seconds'))} |")
    lines.append("")
    return lines


def _fmt_metric_value(entry: dict) -> str:
    if entry.get("type") == "histogram":
        return (f"count={entry.get('count')} sum={entry.get('sum')} "
                f"buckets={entry.get('buckets')}")
    v = entry.get("value")
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _metrics_section(metrics_info: dict, schema_version) -> list:
    """The typed-metrics export (schema v5 ``metrics_snapshot`` events):
    the final registry snapshot plus the per-phase device-memory
    high-water trail.  Placeholder on pre-v5 logs."""
    lines = ["## Metrics", ""]
    metrics_info = metrics_info or {}
    final = metrics_info.get("final")
    if not final:
        if schema_version is not None and schema_version < 5:
            return lines + ["_pre-v5 run log: no metrics_snapshot "
                            "events in this schema version_", ""]
        return lines + ["_no metrics_snapshot events (no metrics "
                        "registry was active)_", ""]
    lines.append(f"- **snapshots**: {metrics_info.get('snapshots', 0)} "
                 f"(the table below is the final, run_end snapshot; "
                 f"wall-clock metrics live in the Prometheus textfile "
                 f"— see obs/metrics_manifest.json)")
    lines += ["", "| metric | type | value |", "|---|---|---|"]
    for key in sorted(final):
        entry = final[key]
        if not isinstance(entry, dict):
            continue
        lines.append(f"| `{key}` | {entry.get('type')} "
                     f"| {_fmt_metric_value(entry)} |")
    hbm = metrics_info.get("hbm_by_phase") or {}
    if hbm:
        lines += ["", "per-phase device HBM high-water "
                      "(max over local devices):", "",
                  "| phase boundary | HBM high-water |", "|---|---:|"]
        for phase, peak in hbm.items():
            lines.append(f"| `{phase}` | {_fmt_bytes(peak)} |")
    lines.append("")
    return lines


def _rescue_section(rescues: list) -> list:
    lines = ["## Mirror rescue", ""]
    if not rescues:
        return lines + ["_no rescue events (mirror_rescue off or "
                        "no step 2)_", ""]
    for ev in rescues:
        cand = ev.get("candidates", 0)
        acc = ev.get("accepted", 0)
        line = (f"- {ev.get('step')}: {cand} boundary-tau candidate(s), "
                f"{acc} accepted")
        if ev.get("capped_to") is not None:
            line += f" (capped to {ev['capped_to']})"
        if ev.get("tau_mean_abs_delta") is not None:
            line += f"; mean |Δtau| {ev['tau_mean_abs_delta']:.3f}"
        lines.append(line)
    lines.append("")
    return lines


def _nan_section(aborts: list) -> list:
    if not aborts:
        return []
    lines = ["## NaN aborts", ""]
    for ev in aborts:
        tail = ev.get("loss_tail", [])
        shown = ", ".join("NaN" if v is None else f"{v:.6g}"
                          for v in tail[-8:])
        lines.append(f"- **{ev.get('step')}** aborted at iteration "
                     f"{ev.get('iters')}; loss tail: {shown}")
    lines.append("")
    return lines


def _run_health_section(path, health_dir=None) -> list:
    """Live-run health rendered from the heartbeat plane (``health/``
    next to the run log, or an explicit ``--health-dir``): per-host
    heartbeat summary, straggler spread, alert verdicts — the same
    renderer ``pert_watch report`` uses.  Placeholder when no
    heartbeats exist (pre-watch runs, heartbeats off)."""
    from scdna_replication_tools_tpu.obs import alerts as alerts_mod
    from scdna_replication_tools_tpu.obs import heartbeat as hb_mod
    from tools.pert_watch import render_health_markdown

    if health_dir is None:
        health_dir = pathlib.Path(str(path)).resolve().parent / "health"
    aggregate = hb_mod.aggregate_health(health_dir)
    try:
        verdicts = alerts_mod.evaluate(alerts_mod.load_rules(),
                                       aggregate)
    except alerts_mod.AlertRuleError:
        verdicts = []
    return render_health_markdown(aggregate, verdicts)


def _meter_section(summary: dict) -> list:
    """The cost/goodput waterfall (schema v9 ``run_end.meter``):
    billed device-seconds -> named waste -> effective, plus goodput
    and the conservation check — the same renderer ``pert_meter
    report`` uses.  Placeholder on pre-v9 / unmetered logs."""
    from tools.pert_meter import render_waterfall

    return render_waterfall(summary.get("meter"))


def render_report(path, health_dir=None) -> str:
    summary = summarize_run(path)
    if summary is None:
        raise SystemExit(f"pert_report: no readable events in {path}")
    lines = _header(summary)
    lines += _run_health_section(path, health_dir)
    lines += _phase_waterfall(summary["phases"])
    lines += _meter_section(summary)
    lines += _spans_section(summary)
    lines += _fit_table(summary["fits"])
    lines += _model_health_section(summary.get("fit_health", []),
                                   summary.get("cell_qc", []))
    lines += _decision_trail_section(summary.get("control_decisions", []),
                                     summary.get("controller", {}))
    lines += _resilience_section(summary.get("resilience", {}),
                                 summary.get("schema_version"))
    lines += _metrics_section(summary.get("metrics", {}),
                              summary.get("schema_version"))
    lines += _compile_section(summary["compile"])
    lines += _rescue_section(summary["rescues"])
    lines += _nan_section(summary["nan_aborts"])
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# --compare
# ---------------------------------------------------------------------------

def _delta(a, b) -> str:
    if a is None or b is None:
        return "-"
    d = b - a
    pct = f" ({d / a:+.0%})" if a else ""
    return f"{d:+.2f}{pct}"


def render_compare(path_a, path_b) -> str:
    sa, sb = summarize_run(path_a), summarize_run(path_b)
    for p, s in ((path_a, sa), (path_b, sb)):
        if s is None:
            raise SystemExit(f"pert_report: no readable events in {p}")
    name_a = pathlib.Path(str(path_a)).name
    name_b = pathlib.Path(str(path_b)).name
    lines = [f"# PERT run comparison — A=`{name_a}` vs B=`{name_b}`", "",
             f"- **A**: status {sa['status']}, wall "
             f"{_fmt_seconds(sa.get('wall_seconds'))}, "
             f"{sa.get('num_devices')}x {sa.get('device_kind')}",
             f"- **B**: status {sb['status']}, wall "
             f"{_fmt_seconds(sb.get('wall_seconds'))}, "
             f"{sb.get('num_devices')}x {sb.get('device_kind')}"]
    ha, hb = sa.get("config_hash"), sb.get("config_hash")
    if ha and hb:
        note = "identical" if ha == hb else f"DIFFER (`{ha}` vs `{hb}`)"
        lines.append(f"- **configs**: {note}")
    wa, wb = sa.get("wall_seconds"), sb.get("wall_seconds")
    if wa and wb:
        lines.append(f"- **wall delta (B - A)**: {_delta(wa, wb)}")
    lines.append("")

    lines += ["## Phases (B - A)", "",
              "| phase | A (s) | B (s) | delta |",
              "|---|---:|---:|---:|"]
    names = sorted(set(sa["phases"]) | set(sb["phases"]),
                   key=lambda n: -(max(sa["phases"].get(n, 0.0),
                                       sb["phases"].get(n, 0.0))))
    for name in names:
        va = sa["phases"].get(name)
        vb = sb["phases"].get(name)
        lines.append(f"| `{name}` "
                     f"| {'-' if va is None else f'{va:.2f}'} "
                     f"| {'-' if vb is None else f'{vb:.2f}'} "
                     f"| {_delta(va, vb)} |")
    lines.append(f"| **total** | {sa['phase_total']:.2f} "
                 f"| {sb['phase_total']:.2f} "
                 f"| {_delta(sa['phase_total'], sb['phase_total'])} |")
    lines.append("")

    lines += ["## Fits (B - A)", "",
              "| step | A iters | B iters | A wall | B wall | wall delta "
              "| A final loss | B final loss |",
              "|---|---:|---:|---:|---:|---:|---:|---:|"]
    fits_a = {f.get("step"): f for f in sa["fits"]}
    fits_b = {f.get("step"): f for f in sb["fits"]}
    for step in sorted(set(fits_a) | set(fits_b), key=str):
        fa, fb = fits_a.get(step, {}), fits_b.get(step, {})
        la, lb = fa.get("final_loss"), fb.get("final_loss")
        lines.append(
            f"| {step} | {fa.get('iters', '-')} | {fb.get('iters', '-')} "
            f"| {_fmt_seconds(fa.get('wall_seconds'))} "
            f"| {_fmt_seconds(fb.get('wall_seconds'))} "
            f"| {_delta(fa.get('wall_seconds'), fb.get('wall_seconds'))} "
            f"| {'-' if la is None else f'{la:.6g}'} "
            f"| {'-' if lb is None else f'{lb:.6g}'} |")
    lines.append("")

    ca, cb = sa["compile"], sb["compile"]
    lines += [
        "## Compile (B - A)", "",
        f"- **A**: {ca['cache_hits']}/{ca['programs']} hits, trace+compile "
        f"{ca['trace_seconds'] + ca['compile_seconds']:.2f}s, peak "
        f"{_fmt_bytes(ca.get('peak_bytes_max'))}",
        f"- **B**: {cb['cache_hits']}/{cb['programs']} hits, trace+compile "
        f"{cb['trace_seconds'] + cb['compile_seconds']:.2f}s, peak "
        f"{_fmt_bytes(cb.get('peak_bytes_max'))}",
        f"- **trace+compile delta**: "
        f"{_delta(ca['trace_seconds'] + ca['compile_seconds'], cb['trace_seconds'] + cb['compile_seconds'])}",
        "",
    ]
    lines += _metrics_compare_section(sa, sb)
    return "\n".join(lines)


def _metrics_compare_section(sa: dict, sb: dict) -> list:
    """Per-metric deltas between two runs with the manifest's regression
    thresholds applied — literally the same judgement as ``pert_fleet
    regress`` (the shared ``obs.metrics.regress_verdict``), inline in a
    run diff.  Uses the shared flat metric vector (final
    metrics_snapshot + event-derived values), so pre-v5 logs still diff
    on their derived metrics."""
    ma, mb = flat_metrics(sa), flat_metrics(sb)
    if not ma and not mb:
        return ["## Metrics (B - A)", "", "_no metrics in either run_",
                ""]
    known = manifest_metrics()
    lines = ["## Metrics (B - A)", "",
             "| metric | A | B | Δ rel | threshold | verdict |",
             "|---|---:|---:|---:|---:|---|"]
    presentation = {"REGRESSED": "⚠ **over threshold**",
                    "untracked": "-"}
    for key in sorted(set(ma) | set(mb)):
        va, vb = ma.get(key), mb.get(key)
        rel = thr = None
        verdict = "-"
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            rel, thr, verdict = regress_verdict(
                known.get(metric_base_name(key)), va, vb)
            verdict = presentation.get(verdict, verdict)
        num = (lambda v: "-" if v is None
               else (f"{v:.6g}" if isinstance(v, float) else str(v)))
        rel_txt = "-" if rel is None or rel != rel \
            or abs(rel) == float("inf") else f"{rel:+.1%}"
        lines.append(
            f"| `{key}` | {num(va)} | {num(vb)} "
            f"| {rel_txt} "
            f"| {'-' if thr is None else f'±{thr:.0%}'} | {verdict} |")
    lines.append("")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Render a RunLog JSONL as markdown, or diff two runs")
    ap.add_argument("run", nargs="?", help="run log (.jsonl) to render")
    ap.add_argument("--compare", nargs=2, metavar=("A", "B"),
                    help="diff two run logs (e.g. a cold/warm "
                         "compile-cache pair) instead of rendering one")
    ap.add_argument("--out", default=None,
                    help="write the markdown here instead of stdout")
    ap.add_argument("--health-dir", default=None,
                    help="heartbeat health/ directory for the 'Run "
                         "health' section (default: health/ next to "
                         "the run log; placeholder when absent)")
    args = ap.parse_args(argv)

    if args.compare:
        report = render_compare(*args.compare)
    elif args.run:
        report = render_report(args.run, health_dir=args.health_dir)
    else:
        ap.print_usage(sys.stderr)
        raise SystemExit("pert_report: give a run log or --compare A B")

    if args.out:
        with open(args.out, "w") as fh:
            fh.write(report + "\n")
    else:
        sys.stdout.write(report + "\n")


if __name__ == "__main__":
    main()
